"""ICI-native hierarchical parameter server: the two-tier gradient plane.

The flat async PS (:mod:`tensorflowonspark_tpu.parallel.ps`) pays a
device→host gradient readback plus a TCP round trip on EVERY step —
measured at ~100× under sync DP on a tunneled chip (BENCH_r05
``bottleneck``), and PR 3's codecs only shrank the wire, not the wall.
This module restructures the plane per the MPI-aggregation literature
(PAPERS.md: "Distributed TensorFlow with MPI", "CUDA-Aware MPI" —
ICI-aware here): keep aggregation on the interconnect, and cross the
host/network boundary only where topology forces it.

Two tiers:

- **Intra-pod (ICI)** — PS shard state (params + optimizer slots) is
  **device-resident**, replicated along the mesh's ``ps`` axis
  (:data:`~tensorflowonspark_tpu.parallel.mesh.AXIS_PS`).  Each step
  is ONE jitted program: grads psum over ICI (XLA inserts the
  collective for the replicated params / ps-sharded batch), the
  optimizer update applies on device, and the step's gradient folds
  into a device-resident accumulation window.  Nothing crosses to the
  host — the ``grad_readback`` telemetry span never fires on this
  path (asserted in tests/test_hier_ps.py).  :func:`ici_mean` /
  :func:`ici_reduce_scatter_mean` expose the explicit shard_map
  collectives for the aggregation math itself.
- **Cross-pod (DCN)** — every ``push_every`` steps the pod's
  accumulated mean gradient window ships to the global PS ensemble
  through the existing compressed wire (error-feedback codecs, delta
  replies — PR 3 intact), but only from the **pod leader**; the reply
  (the globally-mixed params) installs back into the device state
  between steps.  Staleness is bounded by ``max_inflight`` windows.

**Leader election & exactly-once windows.**  Every pod member holds the
identical device-resident state (the ICI tier replicates it), so any
member can take over the DCN duty: the leader is simply the lowest
live member id (:func:`elect_leader`; the supervisor re-elects on
elastic restarts and publishes to the node kv).  Each pushed window
carries a monotonically increasing ``(pod, window)`` id; the server's
ledger applies each id at most once, and a new leader resumes from
``PSClient.window_floor(pod) + 1``, re-pushing its predecessor's
unacknowledged windows — landed-but-unacked ones dedup server-side, so
no gradient is double-applied and none is silently dropped (the
kill-the-leader chaos e2e asserts both, tests/test_chaos.py).  Error
feedback is per-leader-epoch: a fresh leader starts with a clean
residual (its predecessor's residual died with it — bounded, like any
EF state on a crashed worker).

See docs/communication.md "Two-tier gradient plane" for the topology
diagram and tuning guidance.
"""

import logging
import queue as _queue
import threading
import time

import numpy as np

from tensorflowonspark_tpu import compat
from tensorflowonspark_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_PS, build_mesh

logger = logging.getLogger(__name__)


class LeaderKilled(RuntimeError):
    """The pod leader's DCN duty was killed (chaos injection or a real
    wire death) — the signal the trainer's failover path catches to
    re-elect and resume."""


def elect_leader(members, dead=()):
    """The pod's DCN leader: the LOWEST live member id.

    Deterministic and coordination-free — every member computes the
    same answer from the same liveness view, which the heartbeat plane
    already agrees on (the supervisor's re-rendezvous barrier).  Raises
    when nobody is left alive.
    """
    live = sorted(m for m in members if m not in set(dead))
    if not live:
        raise RuntimeError(
            "no live members to elect a leader from: members={0} "
            "dead={1}".format(sorted(members), sorted(dead))
        )
    return live[0]


def current_leader(mgr, default=None):
    """The leader the supervisor published into the node manager kv
    (``hier_leader``), or ``default`` when unset/unreachable — how a
    compute process learns its pod's DCN duty without talking to the
    reservation server itself."""
    try:
        v = mgr.get("hier_leader")
        v = getattr(v, "_getvalue", lambda: v)()
        return default if v is None else int(v)
    except Exception:  # noqa: BLE001 - kv is observability-grade
        return default


# ----------------------------------------------------------------------
# on-device leafwise optimizers (jnp twins of ps.OPTIMIZERS)
# ----------------------------------------------------------------------


class DeviceOptimizer(object):
    """Jittable leafwise optimizer matching the PS server's numpy rules
    (``ps.OPTIMIZERS``) — the apply-update half of the device-resident
    shard.  ``init(params) -> state``; ``update(params, grads, state)
    -> (params, state)``; both pure, both traced into the trainer's
    fused step.  Parity with the numpy implementations is unit-tested
    (tests/test_hier_ps.py), which is what makes the hierarchical
    plane's local tier consistent with the global tier's arithmetic.
    """

    def __init__(self, name, kwargs):
        self.name = name
        self.kwargs = dict(kwargs or {})

    def spec(self):
        return [self.name, dict(self.kwargs)]

    def init(self, params):
        import jax
        import jax.numpy as jnp

        zeros = lambda: jax.tree.map(jnp.zeros_like, params)  # noqa: E731
        if self.name == "sgd":
            if self.kwargs.get("momentum"):
                return {"v": zeros()}
            return {}
        if self.name == "adagrad":
            return {"acc": zeros()}
        if self.name == "adam":
            return {"m": zeros(), "v": zeros(),
                    "t": jnp.zeros((), jnp.int32)}
        raise ValueError(
            "unknown device optimizer {0!r}; supported: "
            "['adagrad', 'adam', 'sgd']".format(self.name)
        )

    def update(self, params, grads, state):
        import jax
        import jax.numpy as jnp

        k = self.kwargs
        if self.name == "sgd":
            lr = k.get("learning_rate", 0.01)
            momentum = k.get("momentum", 0.0)
            if momentum:
                v = jax.tree.map(
                    lambda vv, g: momentum * vv + g, state["v"], grads
                )
                return (
                    jax.tree.map(lambda p, vv: p - lr * vv, params, v),
                    {"v": v},
                )
            return (
                jax.tree.map(lambda p, g: p - lr * g, params, grads),
                state,
            )
        if self.name == "adagrad":
            lr = k.get("learning_rate", 0.01)
            eps = k.get("eps", 1e-10)
            acc = jax.tree.map(
                lambda a, g: a + g * g, state["acc"], grads
            )
            return (
                jax.tree.map(
                    lambda p, g, a: p - lr * g / (jnp.sqrt(a) + eps),
                    params, grads, acc,
                ),
                {"acc": acc},
            )
        if self.name == "adam":
            lr = k.get("learning_rate", 1e-3)
            b1, b2 = k.get("b1", 0.9), k.get("b2", 0.999)
            eps = k.get("eps", 1e-8)
            t = state["t"] + 1
            m = jax.tree.map(
                lambda mm, g: b1 * mm + (1 - b1) * g, state["m"], grads
            )
            v = jax.tree.map(
                lambda vv, g: b2 * vv + (1 - b2) * g * g, state["v"], grads
            )
            tf = t.astype(jnp.float32)
            bc1 = 1 - b1 ** tf
            bc2 = 1 - b2 ** tf
            return (
                jax.tree.map(
                    lambda p, mm, vv: p - lr * (mm / bc1)
                    / (jnp.sqrt(vv / bc2) + eps),
                    params, m, v,
                ),
                {"m": m, "v": v, "t": t},
            )
        raise ValueError("unknown device optimizer {0!r}".format(self.name))


def build_device_optimizer(spec):
    """Resolve a named optimizer spec (the same grammar as the PS
    server's ``_build_optimizer`` — named specs only, never code)."""
    name, kwargs = spec
    return DeviceOptimizer(str(name), kwargs)


# ----------------------------------------------------------------------
# explicit ICI collectives (the aggregation math, shard_map form)
# ----------------------------------------------------------------------


def ici_mean(stacked, mesh, axis=AXIS_PS):
    """psum-mean a per-member gradient stack over the mesh's ``axis``.

    ``stacked`` is a pytree whose leaves carry a leading member dim of
    the axis' width, sharded (or shardable) along ``axis``; the result
    is the member-mean, replicated — one jitted shard_map program, the
    collective running on ICI.  Width-1 (or absent) axes short-circuit
    to a plain squeeze.  The implicit-GSPMD twin of this (replicated
    params + ps-sharded batch inside one jit) is what
    :class:`HierTrainer` rides; this explicit form is the unit-testable
    statement of the aggregation math.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    width = mesh.shape.get(axis, 1)
    if width == 1:
        return jax.tree.map(lambda x: jnp.squeeze(jnp.asarray(x), 0), stacked)

    def body(tree):
        return jax.tree.map(
            lambda x: jax.lax.psum(jnp.squeeze(x, 0), axis) / width, tree
        )

    fn = compat.shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(),
        check_vma=False,
    )
    stacked = jax.tree.map(
        lambda x: jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(axis))
        ),
        stacked,
    )
    return jax.jit(fn)(stacked)


def ici_reduce_scatter_mean(stacked, mesh, axis=AXIS_PS):
    """Reduce-scatter form of :func:`ici_mean`: each shard owns the
    summed 1/width slice of the member-mean (``lax.psum_scatter``
    tiled over the leading data dim), and the ``P(axis)``-stacked
    output reassembles the full mean — bandwidth-optimal when the
    apply-update is itself sharded along ``axis``.  Leaf dim 0 must be
    divisible by the axis width.  Numerically equal to
    :func:`ici_mean` (asserted in tests/test_hier_ps.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    width = mesh.shape.get(axis, 1)
    if width == 1:
        return jax.tree.map(lambda x: jnp.squeeze(jnp.asarray(x), 0), stacked)

    def body(tree):
        return jax.tree.map(
            lambda x: jax.lax.psum_scatter(
                jnp.squeeze(x, 0), axis, scatter_dimension=0, tiled=True
            ) / width,
            tree,
        )

    fn = compat.shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    stacked = jax.tree.map(
        lambda x: jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(axis))
        ),
        stacked,
    )
    return jax.jit(fn)(stacked)


# ----------------------------------------------------------------------
# DCN tier: the pod leader's compressed window pusher
# ----------------------------------------------------------------------


class DcnLink(object):
    """One leader epoch's connection to the global PS ensemble.

    Wraps a :class:`~tensorflowonspark_tpu.parallel.ps.PSClient`
    (compressed pushes under error feedback, delta replies — the PR 3
    wire, untouched) behind a background pusher thread:

    - ``submit(delta, base)`` hands a DEVICE parameter-delta tree (and
      the local params it was measured at) over and returns
      immediately; the thread performs the device→host readback (span
      ``hier.dcn_readback`` — deliberately NOT ``grad_readback``: that
      span is the flat plane's per-step wall, and its absence is the
      hierarchical contract) and the wire round trip off the dispatch
      path.  At most ``max_inflight`` windows may be queued-or-flying
      (bounded staleness; ``submit`` blocks past that).
    - every window carries ``(pod, window_seq)``; the server ledger
      applies each at most once.  ``attach`` resumes the sequence from
      the server's :meth:`~tensorflowonspark_tpu.parallel.ps.PSClient.
      window_floor` — a failover leader continues numbering where the
      ensemble actually is, and re-pushes via :meth:`resubmit`.
    - ``fault_fn(seq)`` is the chaos hook
      (:func:`~tensorflowonspark_tpu.testing.chaos.hier_leader_fault_fn`):
      raising :class:`LeaderKilled` there is exactly what a leader
      death mid-push looks like to the trainer.
    """

    _STOP = object()

    def __init__(self, addresses, optimizer, pod_id="pod0", member_id=0,
                 codec=None, reply_codec=None, error_feedback=True,
                 max_inflight=2, fault_fn=None, timeout=60):
        from tensorflowonspark_tpu import telemetry
        from tensorflowonspark_tpu.parallel.ps import PSClient

        self.pod_id = str(pod_id)
        self.member_id = member_id
        self.optimizer = optimizer
        self.client = PSClient(
            addresses, timeout=timeout, codec=codec,
            reply_codec=reply_codec, error_feedback=error_feedback,
        )
        self._fault_fn = fault_fn
        self._slots = threading.Semaphore(max(1, int(max_inflight)))
        self._q = _queue.Queue()
        self._lock = threading.Lock()
        self._fresh = None
        self.error = None
        self._pushed = []
        self._acked = []
        self._pending = {}  # seq -> device window (submitted, unacked)
        self._next_seq = None
        self.resumed_from = None
        reg = telemetry.get_registry()
        self._m_windows = reg.counter("hier.dcn_windows")
        self._m_dedup = reg.counter("hier.dcn_dedup")
        self._m_rb_hist = reg.histogram("hier.dcn_readback_sec")
        self._m_push_hist = reg.histogram("hier.dcn_push_sec")
        self._tracer = telemetry.get_tracer()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="hier-dcn-%s-m%s" % (self.pod_id, member_id),
        )
        self._thread.start()
        # fleet health plane: the DCN link's state rides /status
        # (telemetry/health.py; one slot per pod, latest link wins —
        # exactly the leader-epoch semantics).  Weakref-bound so a
        # retired leader epoch's link (and its PSClient sockets) is
        # never pinned by the provider registry
        import weakref

        from tensorflowonspark_tpu.telemetry import health as _health

        _ref = weakref.ref(self)

        def _link_status():
            link = _ref()
            return (
                {"retired": True} if link is None
                else link.health_status()
            )

        _health.register_status_provider(
            "hier_ps.%s" % self.pod_id, _link_status
        )

    def health_status(self):
        """Compact DCN-link state for the health plane's ``/status``:
        which member holds the leader duty, how far the window
        sequence has advanced, and the in-flight backlog."""
        with self._lock:
            pending = len(self._pending)
        return {
            "pod": self.pod_id,
            "member": self.member_id,
            "next_window": self._next_seq,
            "resumed_from": self.resumed_from,
            "pushed": len(self._pushed),
            "acked": len(self._acked),
            "inflight": pending,
            "error": str(self.error) if self.error else None,
        }

    # -- lifecycle -----------------------------------------------------

    def attach(self, params_template):
        """Join the global ensemble (idempotent PS init) and resume the
        window sequence from the server's applied floor; returns the
        live global params."""
        live = self.client.init(params_template, self.optimizer)
        self.resync()
        return live

    def resync(self):
        """Re-read the server's applied window floor and resume the
        sequence after it — what a member that just GAINED the leader
        duty does before its first push (its predecessor may have
        advanced the ledger since this link attached)."""
        floor = self.client.window_floor(self.pod_id)
        self._next_seq = floor + 1
        self.resumed_from = floor
        return floor

    def submit(self, delta, base):
        """Queue a device delta window under the next sequence id;
        ``base`` is the local params the delta was measured AT (the
        reply correction anchors on it).  Blocks only when
        ``max_inflight`` windows are already queued-or-flying.
        Returns the sequence assigned."""
        if self._next_seq is None:
            raise RuntimeError("DcnLink.attach() must run before submit()")
        # tfoslint: disable=TFOS006(staleness-window semaphore: the DCN pusher thread releases it when the window lands - cross-thread handoff by design)
        self._slots.acquire()
        seq, self._next_seq = self._next_seq, self._next_seq + 1
        with self._lock:
            self._pending[seq] = (delta, base)
        self._pushed.append(seq)
        self._q.put((seq, delta, base))
        return seq

    def resubmit(self, seq, delta, base):
        """Failover re-push: a predecessor's unacked window, sequence
        preserved — the server ledger dedups it if it actually
        landed."""
        # tfoslint: disable=TFOS006(same staleness-window semaphore handoff as submit)
        self._slots.acquire()
        with self._lock:
            self._pending[seq] = (delta, base)
        self._pushed.append(seq)
        self._q.put((seq, delta, base))

    def _loop(self):
        import jax

        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            if isinstance(item, threading.Event):  # flush marker
                item.set()
                continue
            seq, delta, base = item
            try:
                if self.error is not None:
                    # leader already declared dead: leave the window
                    # pending for the successor instead of pushing on
                    # a broken epoch
                    continue
                t0 = time.perf_counter()
                host = jax.device_get(delta)
                dur = time.perf_counter() - t0
                self._m_rb_hist.observe(dur)
                self._tracer.add(
                    "hier.dcn_readback", t0, dur, trace="hier", window=seq
                )
                if self._fault_fn is not None:
                    self._fault_fn(seq)
                with self._tracer.span(
                    "hier.dcn_push", trace="hier", window=seq,
                    pod=self.pod_id,
                ):
                    fresh = self.client.push_pull(
                        host,
                        header_extra={"pod": self.pod_id, "window": seq},
                    )
                self._m_push_hist.observe(time.perf_counter() - t0)
                self._m_windows.inc()
                with self._lock:
                    self._fresh = (fresh, base)
                    self._pending.pop(seq, None)
                self._acked.append(seq)
            except Exception as e:  # noqa: BLE001 - surfaced to trainer
                if self.error is None:
                    self.error = e
            finally:
                self._slots.release()

    # -- observability -------------------------------------------------

    def fresh(self):
        """Latest landed reply as ``(global host params, base device
        params)`` — cleared on read.  Both states are CUMULATIVE, so
        the newest pair supersedes any skipped intermediates (the
        correction ``global - base`` is everything cross-pod the local
        state hasn't absorbed)."""
        with self._lock:
            fresh, self._fresh = self._fresh, None
        return fresh

    def unacked(self):
        """``{seq: (delta, base)}`` of submitted-but-unacknowledged
        device windows — what a successor re-pushes after failover."""
        with self._lock:
            return dict(self._pending)

    def ledger(self):
        """This epoch's push accounting (the chaos e2e asserts on it)."""
        return {
            "member": self.member_id,
            "pod": self.pod_id,
            "resumed_from": self.resumed_from,
            "pushed": list(self._pushed),
            "acked": list(self._acked),
            "pending": sorted(self.unacked()),
        }

    def flush(self):
        """Block until every queued window was processed (landed or
        parked pending on error)."""
        ev = threading.Event()
        self._q.put(ev)
        ev.wait()

    def stop(self, stop_servers=False):
        self._q.put(self._STOP)
        self._thread.join(timeout=10)
        if stop_servers:
            self.client.stop()
        else:
            self.client.close()


# ----------------------------------------------------------------------
# the hierarchical trainer
# ----------------------------------------------------------------------


class HierTrainer(object):
    """Two-tier async trainer: jitted on-device PS in the pod, compressed
    DCN windows across pods.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` (the
        :class:`~tensorflowonspark_tpu.parallel.ps.AsyncTrainer`
        contract).
      ps_addresses: global PS shard addresses for the DCN tier, or
        None/empty for a single-pod (pure-ICI) run.
      optimizer: named spec for the LOCAL tier's on-device apply
        (:class:`DeviceOptimizer`).  The global tier runs the
        ``delta`` rule — it folds pod deltas in directly, since each
        delta is already the product of this optimizer.
      mesh: mesh carrying a ``ps`` axis (default: all local devices on
        ``ps``).  Params/optimizer state replicate; the batch shards
        along ``(ps, data, fsdp)`` and XLA's gradient psum IS the ICI
        aggregation.
      push_every: ICI steps per DCN window.  A window ships the pod's
        PARAMETER DELTA since the last synced base (``params - ref``);
        the reply's correction (``global - base``) folds the other
        pods' content back in without discarding local progress —
        single-pod runs see a near-zero correction and keep pure
        on-device speed.
      dcn_scale: the global ``delta`` rule's mixing factor (<1 damps
        concurrent many-pod pushes; default 1.0).
      max_inflight: bounded staleness of the DCN tier, in windows.
      codec / reply_codec / error_feedback: the PR 3 wire knobs,
        leader-side.
      pod_id: this pod's ledger namespace on the global shards.
      members / member_id / leader_fn: DCN-duty election.  ``members``
        lists the pod's candidate ids (default: just ``member_id``);
        ``leader_fn()`` overrides the internal lowest-live-member rule
        (production wires :func:`current_leader` over the supervisor's
        kv here).  A non-leader computes identical windows and drops
        them — its state stays bit-identical, which is what makes
        failover a pure bookkeeping step.
      fault_fn: chaos hook forwarded to the :class:`DcnLink`.
      overlap: split the fused step into TWO dispatches — backward
        (grad + the ICI psum XLA appends to it) and psum-consume +
        apply — handed to the runtime back to back WITHOUT a sync, so
        the collective tail of step N's backward overlaps the host's
        dispatch of step N+1 and the DCN readback thread (the
        CUDA-Aware-MPI overlap result, applied to ICI).  The gradient
        accumulators double-buffer: each backward writes fresh
        buffers while the previous step's apply consumes (and, via
        donation, recycles) the prior pair — the backward never
        stalls on an in-flight apply's memory.  Numerics are
        IDENTICAL to the fused step (same op sequence, parity-tested
        in tests/test_hier_ps.py); telemetry spans
        ``hier.overlap_grad`` / ``hier.overlap_apply`` record the
        dispatch pipeline, and the overlap is span-asserted (apply N
        stays open past grad N+1's dispatch).

    ``step(batch)`` returns the (device-resident) params after the
    fused ICI step; no host readback happens anywhere on that path.
    """

    def __init__(self, loss_fn, ps_addresses=None,
                 optimizer=("sgd", {"learning_rate": 0.01}),
                 mesh=None, push_every=8, max_inflight=2, codec=None,
                 reply_codec=None, error_feedback=True, pod_id="pod0",
                 members=None, member_id=0, leader_fn=None,
                 data_axes=(AXIS_PS, AXIS_DATA, AXIS_FSDP),
                 fault_fn=None, timeout=60, dcn_scale=1.0,
                 overlap=False):
        from tensorflowonspark_tpu import telemetry

        if push_every < 1:
            raise ValueError(
                "push_every must be >= 1, got {0}".format(push_every)
            )
        self.loss_fn = loss_fn
        self.optimizer = (optimizer[0], dict(optimizer[1] or {}))
        self.mesh = mesh if mesh is not None else build_mesh({AXIS_PS: -1})
        self.data_axes = data_axes
        self.push_every = int(push_every)
        self.max_inflight = int(max_inflight)
        self.pod_id = str(pod_id)
        self.member_id = member_id
        self.members = tuple(members) if members else (member_id,)
        if member_id not in self.members:
            raise ValueError(
                "member_id {0} not in members {1}".format(
                    member_id, self.members
                )
            )
        self._leader_fn = leader_fn
        self._dead = set()
        self._link_kwargs = dict(
            codec=codec, reply_codec=reply_codec,
            error_feedback=error_feedback, max_inflight=max_inflight,
            fault_fn=fault_fn, timeout=timeout,
        )
        self.dcn_optimizer = ("delta", {"scale": float(dcn_scale)})
        self.addresses = list(ps_addresses or [])
        self._opt = build_device_optimizer(self.optimizer)
        self._state = None      # (params, opt_state) device trees
        self._ref = None        # last synced base (device tree)
        self._window_steps = 0
        self._was_leader = False
        self._loss = None       # device scalar of the last step
        self._link = None
        self._epochs = []       # closed DcnLink ledgers (failover audit)
        self._step_fn = None
        self._sub_fn = None
        self._copy_fn = None
        self._corr_fn = None
        self.overlap = bool(overlap)
        self._grad_fn = None
        self._apply_fn = None
        self._apply_open = None  # (t0, step_idx) of the in-flight apply
        self._step_idx = 0
        reg = telemetry.get_registry()
        self._m_steps = reg.counter("hier.ici_steps")
        self._m_failover = reg.counter("hier.leader_failovers")
        self._g_leader = reg.gauge("hier.leader")
        self._tracer = telemetry.get_tracer()
        if self.addresses:
            self._open_link()

    # -- live retune ---------------------------------------------------

    def set_push_every(self, push_every):
        """Retune the ICI-steps-per-DCN-window cadence in place.

        Safe mid-training: ``push_every`` is read at every step's
        window check, so the new cadence takes effect at the next
        window boundary — no quiesce, no link rebuild.  This is the
        actuation seam the live re-planner drives when measured DCN
        RTT drifts off the planned cadence (push_every x step_time >
        RTT).  Returns the previous value.
        """
        push_every = int(push_every)
        if push_every < 1:
            raise ValueError(
                "push_every must be >= 1, got {0}".format(push_every)
            )
        old = self.push_every
        self.push_every = push_every
        if push_every != old:
            self._tracer.mark(
                "push_every_retune", trace="hier_ps",
                old=old, new=push_every, pod=self.pod_id,
            )
        return old

    # -- election ------------------------------------------------------

    def leader(self):
        """The current DCN leader's member id."""
        if self._leader_fn is not None:
            got = self._leader_fn()
            if got is not None:
                return got
        return elect_leader(self.members, self._dead)

    def acting_member(self):
        """The member identity this trainer's DCN duty currently acts
        as.  Normally ``member_id``; after an in-process failover
        (single-process pod: all candidate members live in this
        trainer) it is the successor epoch's id — the live link's."""
        return (
            self._link.member_id if self._link is not None
            else self.member_id
        )

    def is_leader(self):
        return self.leader() == self.acting_member()

    def _open_link(self, member_id=None):
        member_id = self.member_id if member_id is None else member_id
        self._link = DcnLink(
            self.addresses, self.dcn_optimizer, pod_id=self.pod_id,
            member_id=member_id, **self._link_kwargs
        )
        self._g_leader.set(member_id)
        self._tracer.mark(
            "leader_elected", trace="hier", pod=self.pod_id,
            member=member_id,
        )

    @property
    def client(self):
        """The DCN tier's PSClient (wire accounting lives there), or
        None on a pure-ICI run."""
        return self._link.client if self._link is not None else None

    def dcn_epochs(self):
        """Every leader epoch's ledger, oldest first, the live one
        last — the failover audit the chaos e2e asserts on."""
        out = list(self._epochs)
        if self._link is not None:
            out.append(self._link.ledger())
        return out

    # -- jitted programs -----------------------------------------------

    def _build_step(self):
        import jax

        loss_fn, opt = self.loss_fn, self._opt

        def fused(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            new_params, new_opt = opt.update(params, grads, opt_state)
            return new_params, new_opt, loss

        # donation recycles the whole shard state in place: the apply-
        # update IS the on-device program, there is no host copy to
        # invalidate
        return jax.jit(fused, donate_argnums=(0, 1))

    def _build_split_step(self):
        """The overlapped pair (``overlap=True``): backward (whose
        tail is the ICI psum GSPMD appends for the replicated params)
        and psum-consume + apply, dispatched back to back with no
        intervening sync.  The grads tree is the double-buffered
        accumulator: each backward call produces a FRESH buffer pair
        while the previous pair is being consumed — and donated, so
        the runtime recycles it — by the in-flight apply."""
        import jax

        loss_fn, opt = self.loss_fn, self._opt
        grad_fn = jax.jit(jax.value_and_grad(loss_fn))

        def apply(params, opt_state, grads):
            return opt.update(params, grads, opt_state)

        apply_fn = jax.jit(apply, donate_argnums=(0, 1, 2))
        return grad_fn, apply_fn

    def _overlap_step(self, params, opt_state, batch):
        """One overlapped step: dispatch backward, close the PREVIOUS
        step's apply span (it was held open across this dispatch — the
        recorded overlap), dispatch apply, leave its span open."""
        t_grad = time.perf_counter()
        with self._tracer.span(
            "hier.overlap_grad", trace="hier", step=self._step_idx,
        ):
            loss, grads = self._grad_fn(params, batch)
        if self._apply_open is not None:
            t0, idx = self._apply_open
            # the previous apply's pipeline interval ends only now —
            # AFTER this step's backward was dispatched: that ordering
            # is the overlap, and the span records it
            self._tracer.add(
                "hier.overlap_apply", t0, time.perf_counter() - t0,
                trace="hier", step=idx,
            )
        self._apply_open = (time.perf_counter(), self._step_idx)
        del t_grad
        new_params, new_opt = self._apply_fn(params, opt_state, grads)
        self._step_idx += 1
        return new_params, new_opt, loss

    def _close_overlap_span(self):
        if self._apply_open is not None:
            t0, idx = self._apply_open
            self._apply_open = None
            self._tracer.add(
                "hier.overlap_apply", t0, time.perf_counter() - t0,
                trace="hier", step=idx,
            )

    def _build_helpers(self):
        import jax
        import jax.numpy as jnp

        # window close: delta vs the synced base, plus a fresh-buffer
        # copy of params (the live tree is DONATED into every step, so
        # the base must own its buffers)
        self._sub_fn = jax.jit(
            lambda a, b: jax.tree.map(lambda x, y: x - y, a, b)
        )
        self._copy_fn = jax.jit(
            lambda t: jax.tree.map(jnp.copy, t)
        )
        # reply install: fold the cross-pod correction (global - base)
        # into BOTH the live params and the base, preserving local
        # progress made while the window flew
        # no donation here: base/ref may alias across the two installs
        # (params and ref both correct against the same base tree)
        self._corr_fn = jax.jit(
            lambda p, g, b: jax.tree.map(
                lambda pp, gg, bb: pp + (gg - bb), p, g, b
            )
        )

    # -- lifecycle -----------------------------------------------------

    def init(self, params):
        """Place the PS shard state on device (params replicated over
        the mesh, optimizer slots alongside) and join the global
        ensemble when a DCN tier is configured; returns the device
        params."""
        import jax

        from tensorflowonspark_tpu.parallel import sharding as sh

        if self._link is not None:
            # seed/join the global tier first: a restarted pod adopts
            # the globally-live params instead of its init template
            params = self._link.attach(params)
        device_params = jax.tree.map(
            lambda p: jax.device_put(np.asarray(p), sh.replicated(self.mesh)),
            params,
        )
        opt_state = jax.jit(self._opt.init)(device_params)
        opt_state = sh.canonicalize_on_mesh(opt_state, self.mesh)
        self._state = (device_params, opt_state)
        if self._step_fn is None:
            self._step_fn = self._build_step()
            if self.overlap:
                self._grad_fn, self._apply_fn = self._build_split_step()
            self._build_helpers()
        # the synced base starts at the (globally-agreed) init params;
        # its buffers are its own — the live tree is donated every step
        self._ref = self._copy_fn(device_params)
        self._window_steps = 0
        self._was_leader = self.is_leader() if self._link else False
        return device_params

    @property
    def params(self):
        """The device-resident params (no copy, no readback)."""
        if self._state is None:
            raise RuntimeError("call init(params) first")
        return self._state[0]

    def last_loss(self):
        """Device scalar loss of the most recent step (pull it to host
        only when YOU want the sync)."""
        return self._loss

    # -- the step ------------------------------------------------------

    def step(self, batch):
        """One in-pod step: fused grad + ICI aggregation + on-device
        apply + window fold, one dispatch, zero host transfers.  At
        ``push_every`` cadence the leader ships the window to the DCN
        tier (background thread); a landed reply's global params
        install before the NEXT step (host→device only)."""
        import jax

        from tensorflowonspark_tpu.parallel import sharding as sh

        if self._state is None:
            raise RuntimeError("call init(params) first")
        self._check_link()
        self._install_fresh()
        if batch is not None:
            batch = sh.shard_batch(batch, self.mesh, self.data_axes)
        params, opt_state = self._state
        if self.overlap:
            params, opt_state, self._loss = self._overlap_step(
                params, opt_state, batch
            )
        else:
            params, opt_state, self._loss = self._step_fn(
                params, opt_state, batch
            )
        self._state = (params, opt_state)
        self._window_steps += 1
        self._m_steps.inc()
        if self._link is not None and self._window_steps >= self.push_every:
            self._close_window()
        return params

    def _close_window(self):
        lead = self.is_leader()
        if lead and not self._was_leader:
            # just GAINED the duty (supervisor re-election): resume the
            # window sequence from the server's ledger, not from this
            # link's stale attach-time floor
            self._link.resync()
        self._was_leader = lead
        params = self._state[0]
        if lead:
            delta = self._sub_fn(params, self._ref)
            base = self._copy_fn(params)
            self._ref = base
            self._link.submit(delta, base)
        else:
            # non-leaders advance the base identically (their window
            # would be the same ICI-aggregated tree — pushing it too
            # would double-count); keeping the base in lockstep is what
            # makes a takeover's first delta start from the right spot
            self._ref = self._copy_fn(params)
        self._window_steps = 0

    def _install_fresh(self):
        import jax

        from tensorflowonspark_tpu.parallel import sharding as sh

        if self._link is None:
            return
        fresh = self._link.fresh()
        if fresh is None:
            return
        if jax.process_count() > 1:
            # a multi-process pod must install the correction
            # identically on every process; only the leader holds the
            # reply, so the install rides the next re-rendezvous
            # instead (documented limitation — docs/communication.md)
            logger.warning(
                "skipping cross-pod correction install on a "
                "multi-process pod (leader-only reply)"
            )
            return
        global_host, base = fresh
        device_global = jax.tree.map(
            lambda p: jax.device_put(
                np.asarray(p), sh.replicated(self.mesh)
            ),
            global_host,
        )
        # fold (global - base) into the live params AND the synced
        # base: local progress made while the window flew is preserved,
        # and the next delta measures pure local content
        params, opt_state = self._state
        self._state = (
            self._corr_fn(params, device_global, base), opt_state
        )
        self._ref = self._corr_fn(self._ref, device_global, base)

    # -- failover ------------------------------------------------------

    def _check_link(self):
        if self._link is None or self._link.error is None:
            return
        err = self._link.error
        survivors = [
            m for m in self.members
            if m not in self._dead and m != self._link.member_id
        ]
        retriable = isinstance(
            err, (LeaderKilled, ConnectionError, OSError, RuntimeError)
        )
        if not survivors or not retriable:
            raise err
        # the leader epoch died: record it, elect the next member, and
        # hand the dead epoch's unacked windows to the successor (the
        # server ledger dedups any that actually landed).  This trainer
        # then ACTS as the successor — the single-process-pod model,
        # where every candidate member lives in this trainer.  In a
        # multi-process pod each process passes members=[own_id] plus a
        # supervisor-backed leader_fn, so a dead leader's duty moves to
        # another PROCESS (via re-election + resync) and this path
        # correctly re-raises instead of impersonating.
        dead_link = self._link
        self._dead.add(dead_link.member_id)
        self._m_failover.inc()
        # flight-recorder dump trigger (telemetry/blackbox.py): the
        # DCN leader died mid-push — exactly the incident the
        # forensics analyzer reconstructs from this process's rings
        self._tracer.mark(
            "leader_failover", trace="hier", severity="page",
            pod=self.pod_id, dead_member=dead_link.member_id,
            error=str(err),
        )
        logger.warning(
            "pod %s leader (member %s) died mid-push (%s); re-electing",
            self.pod_id, dead_link.member_id, err,
        )
        dead_link.flush()
        pending = dead_link.unacked()
        self._epochs.append(dead_link.ledger())
        dead_link.stop()
        new_leader = elect_leader(self.members, self._dead)
        self._open_link(member_id=new_leader)
        # attach with the CURRENT device params as template (idempotent
        # join — the live global values win, our template is ignored)
        import jax

        self._link.attach(jax.device_get(self._state[0]))
        self._was_leader = self.is_leader()
        floor = self._link.resumed_from
        resubmitted = 0
        for seq in sorted(pending):
            if seq > floor:
                delta, base = pending[seq]
                self._link.resubmit(seq, delta, base)
                resubmitted += 1
        # the successor continues numbering AFTER the retained windows
        self._link._next_seq = max(
            self._link._next_seq, (max(pending) + 1) if pending else 0
        )
        logger.info(
            "pod %s: member %s took over the DCN duty (floor %d, "
            "%d window(s) re-pushed)",
            self.pod_id, new_leader, floor, resubmitted,
        )

    # -- drain / feed / teardown ---------------------------------------

    def drain(self):
        """Ship a partial window (leader), wait for every in-flight DCN
        window to land, and install the final cross-pod correction;
        returns the device params.  Raises a non-retriable link error;
        a retriable one re-elects first."""
        self._close_overlap_span()
        if self._link is not None:
            self._check_link()
            if self._window_steps and self._state is not None:
                self._close_window()
            self._link.flush()
            self._check_link()
            self._link.flush()
            self._install_fresh()
        return self._state[0] if self._state is not None else None

    def train_on_feed(self, feed, batch_size, preprocess=None,
                      max_steps=None, columnar=False, step_callback=None,
                      log_every=100):
        """Feed-driven hierarchical training: pull globally-agreed
        batches (the same all-hosts barrier as
        :meth:`~tensorflowonspark_tpu.parallel.dp.SyncTrainer.
        train_on_feed` — every pod process steps the same count, so the
        ICI collective never strands a straggler) and run :meth:`step`
        per batch.  Returns the step count."""
        from tensorflowonspark_tpu.parallel import dp

        steps = 0
        while True:
            if max_steps is not None and steps >= max_steps:
                break
            group, stopped = dp.collect_ready_group(
                feed, batch_size, 1, columnar=columnar,
                preprocess=preprocess,
            )
            if not group:
                if stopped:
                    logger.info("global stop after %d steps", steps)
                break
            if step_callback is not None:
                step_callback(steps)
            self.step(group[0])
            steps += 1
            if log_every and steps % log_every == 0:
                logger.info("hier step %d", steps)
            if stopped:
                logger.info("global stop after %d steps", steps)
                break
        self.drain()
        return steps

    def stop(self, stop_servers=False):
        try:
            if self._link is not None:
                self.drain()
        except Exception:  # noqa: BLE001 - teardown must proceed
            pass
        if self._link is not None:
            self._epochs.append(self._link.ledger())
            self._link.stop(stop_servers=stop_servers)
            self._link = None
