"""Tensor parallelism: sharded-matmul strategy surface.

The reference had no TP implementation — its README's "model
parallelism" claim rested on arbitrary clusterspec job names letting
TF1 users place ops by device scope (reference: README.md:45, SURVEY.md
§2.3).  Here TP is a first-class mesh program: parameters carry logical
axis names, a rule set maps them onto the ``model`` mesh axis, and XLA
inserts the all-reduces over ICI.

This module is the strategy-level API; the mechanics live in
:mod:`tensorflowonspark_tpu.parallel.sharding` (rule application) and
the model zoo's logical annotations.  Megatron-style pairing: shard the
up-projection column-wise (``ffn_in``), the down-projection row-wise
(``ffn_out``), attention heads across ``model`` — one psum per block.
"""

import functools

import jax
from jax import lax

from tensorflowonspark_tpu.parallel.mesh import AXIS_TENSOR  # noqa: F401
from tensorflowonspark_tpu.parallel.sharding import (  # noqa: F401
    apply_rules,
    param_specs,
    shard_params,
)


# -- manual-mode TP collectives (Megatron's f/g operators) -----------------
#
# Inside ``shard_map`` code (where the PipelineTrainer schedules run) the
# GSPMD rule machinery above doesn't apply — TP needs its collectives
# written out, and under ``check_vma=False`` a bare ``lax.psum`` inside
# the differentiated region transposes to another psum (scaling
# gradients by the axis size).  These two custom-vjp ops pin the exact
# Megatron semantics instead: ``tp_copy`` enters a TP region (identity
# forward, gradient all-reduce — the input is replicated across
# ``model``, so each shard's contribution to its cotangent must sum);
# ``tp_reduce`` exits it (all-reduce forward, identity backward — the
# output becomes replicated, so the replicated cotangent passes
# through).  Column-parallel matmul, then row-parallel, then one
# ``tp_reduce``: one psum per block, gradients exact.

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_copy(x, axis_name="model"):
    """Enter a tensor-parallel region: identity fwd, psum bwd."""
    return x


def _tp_copy_fwd(x, axis_name):
    return x, None


def _tp_copy_bwd(axis_name, _, ct):
    return (lax.psum(ct, axis_name),)


tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x, axis_name="model"):
    """Exit a tensor-parallel region: psum fwd, identity bwd."""
    return lax.psum(x, axis_name)


def _tp_reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _tp_reduce_bwd(axis_name, _, ct):
    return (ct,)


tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)

#: Megatron-style rule set for the model zoo's logical axis names:
#: embed stays replicated across ``model``; FFN in/out split col/row;
#: attention heads split across ``model``.
TP_RULES = (
    ("embed", None),
    ("vocab", "model"),
    ("heads", "model"),
    ("kv", None),
    ("ffn", "model"),
    ("seq", None),
)


def tensor_parallel_specs(abstract_params, mesh, rules=TP_RULES, annotations=None):
    """PartitionSpecs placing params for TP on ``mesh``'s ``model`` axis.

    Args:
      abstract_params: pytree of ShapeDtypeStructs (or arrays).
      mesh: a Mesh with a ``model`` axis (see
        :func:`tensorflowonspark_tpu.parallel.mesh.build_mesh`).
      rules: (logical_axis, mesh_axis) pairs.
      annotations: optional explicit logical specs per leaf path.
    """
    return param_specs(abstract_params, rules, mesh=mesh, annotations=annotations)


def trainer(loss_fn, optimizer, mesh, annotations, fsdp=False, **kw):
    """A :class:`~tensorflowonspark_tpu.parallel.dp.SyncTrainer` wired
    for tensor parallelism (optionally + FSDP): annotated params shard
    onto the ``model`` (and ``fsdp``) axes, XLA inserts the per-block
    psums over ICI.  This is the one-call TP entry point the model zoo
    examples use."""
    from tensorflowonspark_tpu.parallel import dp, sharding as sh

    rules = sh.RULES_TP_FSDP if fsdp else sh.RULES_TP
    return dp.SyncTrainer(
        loss_fn,
        optimizer,
        mesh=mesh,
        rules=rules,
        annotations=annotations,
        **kw,
    )


def validate(params, annotations, mesh, rules=None):
    """Pre-flight check of a TP placement.

    Reports per-device parameter bytes before/after sharding and every
    dimension a rule *targeted* but could not shard (non-divisible
    size, or the mesh axis was already consumed) — the classic TP
    mistakes (head count not divisible by the ``model`` axis; a dim
    silently left replicated), caught BEFORE a multi-minute pod compile
    does.  Returns a report dict; raises nothing.
    """
    import jax.tree_util as jtu
    import numpy as np

    from tensorflowonspark_tpu.parallel import sharding as sh

    rules = sh.RULES_TP if rules is None else rules
    rule_map = dict(rules)
    specs = param_specs(params, rules, mesh=mesh, annotations=annotations)

    # flatten annotations/specs UP TO params' structure so a tuple/list
    # *container* inside params never swallows its annotation leaves
    # (the mechanism jax.tree.map itself uses for multi-tree mapping)
    paths_and_leaves, treedef = jtu.tree_flatten_with_path(params)
    leaves = paths_and_leaves
    spec_leaves = treedef.flatten_up_to(specs)
    ann_leaves = (
        treedef.flatten_up_to(annotations)
        if annotations is not None
        else [None] * len(leaves)
    )

    total = per_device = 0
    unsharded = []
    for (path, leaf), spec, ann in zip(leaves, spec_leaves, ann_leaves):
        shape = tuple(getattr(leaf, "shape", ()))
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(
            getattr(leaf, "dtype", np.float32)
        ).itemsize
        total += nbytes
        placed = tuple(spec) if spec is not None else ()
        width = 1
        for axes in placed:
            for a in () if axes is None else (
                (axes,) if isinstance(axes, str) else axes
            ):
                width *= mesh.shape.get(a, 1)
        per_device += nbytes // max(1, width)
        for i, logical in enumerate(ann or ()):
            target = rule_map.get(logical) if logical else None
            if target is None:
                continue
            first_axis = target if isinstance(target, str) else target[0]
            got = placed[i] if i < len(placed) else None
            if got is None and mesh.shape.get(first_axis, 1) > 1:
                unsharded.append((jtu.keystr(path), i, logical, shape))
    return {
        "total_param_bytes": total,
        "per_device_param_bytes": per_device,
        "sharding_ratio": total / max(1, per_device),
        "unsharded_targeted_dims": unsharded,
    }
