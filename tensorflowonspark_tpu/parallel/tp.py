"""Tensor parallelism: sharded-matmul strategy surface.

The reference had no TP implementation — its README's "model
parallelism" claim rested on arbitrary clusterspec job names letting
TF1 users place ops by device scope (reference: README.md:45, SURVEY.md
§2.3).  Here TP is a first-class mesh program: parameters carry logical
axis names, a rule set maps them onto the ``model`` mesh axis, and XLA
inserts the all-reduces over ICI.

This module is the strategy-level API; the mechanics live in
:mod:`tensorflowonspark_tpu.parallel.sharding` (rule application) and
the model zoo's logical annotations.  Megatron-style pairing: shard the
up-projection column-wise (``ffn_in``), the down-projection row-wise
(``ffn_out``), attention heads across ``model`` — one psum per block.
"""

from tensorflowonspark_tpu.parallel.mesh import AXIS_TENSOR  # noqa: F401
from tensorflowonspark_tpu.parallel.sharding import (  # noqa: F401
    apply_rules,
    param_specs,
    shard_params,
)

#: Megatron-style rule set for the model zoo's logical axis names:
#: embed stays replicated across ``model``; FFN in/out split col/row;
#: attention heads split across ``model``.
TP_RULES = (
    ("embed", None),
    ("vocab", "model"),
    ("heads", "model"),
    ("kv", None),
    ("ffn", "model"),
    ("seq", None),
)


def tensor_parallel_specs(abstract_params, mesh, rules=TP_RULES, annotations=None):
    """PartitionSpecs placing params for TP on ``mesh``'s ``model`` axis.

    Args:
      abstract_params: pytree of ShapeDtypeStructs (or arrays).
      mesh: a Mesh with a ``model`` axis (see
        :func:`tensorflowonspark_tpu.parallel.mesh.build_mesh`).
      rules: (logical_axis, mesh_axis) pairs.
      annotations: optional explicit logical specs per leaf path.
    """
    return param_specs(abstract_params, rules, mesh=mesh, annotations=annotations)
