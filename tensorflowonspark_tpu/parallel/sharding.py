"""Logical-axis sharding rules → concrete ``PartitionSpec``/shardings.

Models annotate parameters with *logical* axis names ("embed", "mlp",
"heads", "batch", ...); strategies pick a rule set mapping logical names
to mesh axes.  This is the layer that makes one model definition run
under DP, FSDP, TP, or any combination — the reference had no analogue
(all sharding lived inside TF's strategies).

Rules are ordered ``(logical_axis, mesh_axis_or_None)`` pairs; the first
match wins.  A mesh axis already consumed for an earlier dimension of the
same spec is skipped (a mesh axis may shard at most one dimension of a
given array).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)

# Default rule sets per strategy (models use these logical names).
RULES_DP = (
    ("batch", ("data", "fsdp")),
)
RULES_FSDP = RULES_DP + (
    ("embed", "fsdp"),
    ("mlp", "fsdp"),
    ("vocab", "fsdp"),
)
RULES_TP = RULES_DP + (
    ("mlp", "model"),
    ("heads", "model"),
    ("kv_heads", "model"),
    ("vocab", "model"),
    ("expert", "expert"),
    ("expert_mlp", "model"),
)
RULES_TP_FSDP = RULES_DP + (
    ("mlp", "model"),
    ("heads", "model"),
    ("kv_heads", "model"),
    # vocab takes model AND fsdp: for the embedding table this puts all
    # sharding on the gather/scatter dim and leaves the embed dim
    # replicated (the t5x/maxtext layout).  Sharding embed on fsdp here
    # instead forces the partitioner to reshard the gather's output from
    # batch sharding to embed sharding in the backward scatter — an
    # "involuntary full rematerialization" at every step.  For matmul
    # params (lm_head) fsdp is already consumed by the embed dim by the
    # time vocab resolves, so their specs are unchanged.
    ("vocab", ("model", "fsdp")),
    ("embed", "fsdp"),
    ("expert", "expert"),
    ("expert_mlp", "model"),
)
RULES_SEQ = (
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),
)
RULES_EP = RULES_DP + (
    ("expert", "expert"),
    ("expert_mlp", "model"),
)


def apply_rules(logical_spec, rules, mesh=None, shape=None):
    """Map a tuple of logical axis names (or ``None``) to a
    :class:`PartitionSpec` under ``rules``.

    Mesh axes absent from ``mesh`` (when given) resolve to ``None`` —
    this is what lets TP-annotated models run unmodified on a pure-DP
    mesh.  With ``shape`` given, mesh axes that would not divide the
    dimension are dropped (e.g. a single-head model under TP: ``heads``
    is size 1, so the ``model`` axis falls off rather than erroring in
    ``device_put``).
    """
    rule_map = dict(rules) if not isinstance(rules, dict) else rules
    used = set()
    out = []
    for i, logical in enumerate(logical_spec):
        mesh_axes = rule_map.get(logical) if logical is not None else None
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        dim = shape[i] if shape is not None and i < len(shape) else None
        width = 1
        picked = []
        for ax in mesh_axes:
            if ax in used:
                continue
            size = mesh.shape.get(ax, 1) if mesh is not None else 1
            if mesh is not None and size == 1:
                # absent/size-1 axis: harmless to include, but dropping it
                # keeps specs readable in logs
                continue
            if dim is not None and dim % (width * size) != 0:
                continue
            picked.append(ax)
            used.add(ax)
            width *= size
        if not picked:
            out.append(None)
        elif len(picked) == 1:
            out.append(picked[0])
        else:
            out.append(tuple(picked))
    # trailing Nones are implied
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def param_specs(abstract_params, rules, mesh=None, annotations=None):
    """Derive a ``PartitionSpec`` pytree for a parameter pytree.

    Args:
      abstract_params: pytree of arrays / ShapeDtypeStructs.
      rules: logical→mesh rules.
      annotations: optional matching pytree of logical-axis tuples (as
        produced by :func:`tensorflowonspark_tpu.models.base.logical_axes`).
        Leaves without annotation are sharded by a shape heuristic: the
        largest dimension divisible by the fsdp axis size goes on
        ``fsdp`` (zero-3 style) if an ``fsdp`` rule target exists,
        otherwise fully replicated.
    """
    fsdp_size = mesh.shape.get("fsdp", 1) if mesh is not None else 1

    def _spec_for(leaf, logical):
        if logical is not None:
            return apply_rules(
                logical, rules, mesh, shape=getattr(leaf, "shape", None)
            )
        shape = getattr(leaf, "shape", ())
        if fsdp_size > 1 and len(shape) >= 1:
            # shape heuristic for un-annotated params
            dims = sorted(
                range(len(shape)), key=lambda i: shape[i], reverse=True
            )
            for d in dims:
                if shape[d] % fsdp_size == 0 and shape[d] >= fsdp_size:
                    spec = [None] * len(shape)
                    spec[d] = "fsdp"
                    while spec and spec[-1] is None:
                        spec.pop()
                    return PartitionSpec(*spec)
        return PartitionSpec()

    if annotations is None:
        return jax.tree.map(lambda l: _spec_for(l, None), abstract_params)
    return jax.tree.map(
        _spec_for,
        abstract_params,
        annotations,
        is_leaf=lambda x: x is None,
    )


def shard_params(params, rules, mesh, annotations=None):
    """Place a parameter pytree onto the mesh per the rules.

    Always copies: ``device_put`` may alias the source buffer into a
    shard of the placed array, and trainers *donate* the placed state —
    aliased donation would silently delete the caller's original params
    (e.g. re-using the same init params for a second trainer).
    """
    specs = param_specs(params, rules, mesh, annotations)
    return jax.tree.map(
        lambda p, s: jax.device_put(
            jnp.array(p), NamedSharding(mesh, s)
        ),
        params,
        specs,
    )


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def canonicalize_on_mesh(tree, mesh):
    """Ensure every leaf lives on ``mesh``.  Leaves XLA left on a single
    device (jit outputs with no input dependence — e.g. optax ``count``
    scalars) are re-placed replicated; mesh-sharded leaves pass through.
    A state that mixes single-device and mesh arrays fails at the next
    jitted step with 'incompatible devices', and checkpoint templates
    built from it restore to the same broken placement."""

    def _fix(x):
        s = getattr(x, "sharding", None)
        if s is None or not hasattr(x, "shape"):
            return x
        if isinstance(s, NamedSharding) and s.mesh == mesh:
            return x
        return jax.device_put(x, replicated(mesh))

    return jax.tree.map(_fix, tree)


def batch_sharding(mesh, data_axes=("data", "fsdp")):
    """Sharding for a ``[batch, ...]`` array: batch dim split over the
    data-parallel axes (only the ones present on the mesh)."""
    present = tuple(a for a in data_axes if mesh.shape.get(a, 1) > 1)
    if not present:
        return NamedSharding(mesh, PartitionSpec())
    axes = present[0] if len(present) == 1 else present
    return NamedSharding(mesh, PartitionSpec(axes))


def shard_batch(batch, mesh, data_axes=("data", "fsdp"), leading_dims=0):
    """Place a host batch (pytree of np/jnp arrays, leading batch dim)
    onto the mesh, split over the data axes.

    Single-process: a straight ``device_put`` with the batch sharding.
    Multi-process: each host owns a slice of the global batch; assembled
    via ``make_array_from_process_local_data`` (the HBM landing zone of
    the reference's InputMode.SPARK feed path, SURVEY.md §2.3).

    Args:
      leading_dims: number of replicated axes *before* the batch dim —
        e.g. 1 for the ``[K, batch, ...]`` stacks that
        ``SyncTrainer.multi_step`` scans over.
    """
    base = batch_sharding(mesh, data_axes)
    spec = PartitionSpec(*(((None,) * leading_dims) + tuple(base.spec)))
    sharding = NamedSharding(mesh, spec)
    width = 1
    for a in data_axes:
        width *= mesh.shape.get(a, 1)

    def _check(x):
        ndim = getattr(x, "ndim", 0)
        n = x.shape[leading_dims] if ndim > leading_dims else 0
        if width > 1 and n % width != 0:
            raise ValueError(
                "batch dim {0} not divisible by data-parallel width {1}; "
                "pad or resize the batch (global batch must be a multiple "
                "of the data axes' product)".format(n, width)
            )
        return x

    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(_check(x), sharding), batch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(_check(x))
        ),
        batch,
    )
