"""Device-resident radix prefix cache for cross-request KV reuse.

At fleet traffic most prompts share long prefixes (system prompts,
few-shot headers), yet a cold serving engine prefills every request
from token 0.  This module is the request-level reuse plane for the
continuous-batching engine: a radix tree over token prefixes whose
nodes own *committed KV blocks* — device-resident slices of the slot
table's per-layer key/value banks — so an admit whose prompt extends a
cached prefix installs the cached banks into its slot's lanes and
prefills only the uncached suffix
(:meth:`~tensorflowonspark_tpu.models.transformer.SlotDecoder.admit`).

Design notes:

- **Fixed-width radix edges.**  The tree is indexed in blocks of
  ``block_tokens`` tokens: every node is exactly one block, keyed by
  its token content, and a path root→node spells a prompt prefix in
  whole blocks.  Fixed-width edges keep lookup O(prompt/block) dict
  hops, make sharing *block-granular* (two prompts share exactly the
  blocks their token prefixes share), and — critically — match the
  device layout: one node == one contiguous ``[block, heads, dim]``
  slice per cache leaf, installable with a single
  ``dynamic_update_slice`` per admit.
- **Canonical positions.**  Cached keys are post-RoPE, so a block is
  only reusable at the *same* physical cache positions it was written
  at.  The cache therefore stores blocks at canonical positions
  (token ``i`` of the prompt lives at cache position ``i``), and the
  SlotDecoder's cached-prefix admit path places every request at
  canonical positions too (right-padded prefill — see
  ``SlotDecoder._prefill_canonical``).  Outputs stay token-identical
  to a cold run: RoPE scores depend only on position differences, the
  same invariant the ragged left-pad parity tests pin down.
- **Refcounted sharing + LRU leaf eviction.**  A lookup *pins* its
  matched path (refcount) until the admit's install dispatches are
  enqueued; eviction only ever removes cold *leaves* (no children, no
  pins), oldest-``last_used`` first, so a shared interior block
  outlives every prompt family built on it.
- **Memory accounting against the slot table's HBM budget.**  Every
  block's device bytes are accounted; inserts evict cold branches to
  stay under ``mem_budget_bytes``, and the serving engine's degrade
  policy calls :meth:`evict_cold` under backlog pressure *before*
  shrinking token budgets (cold cache is the cheapest thing to give
  back — see docs/serving.md "Prefix cache & speculative decoding").

The payloads are opaque to this module (the SlotDecoder passes device
pytrees on the contiguous layout, physical PAGE INDICES on the paged
layout — see :class:`PagePool`); all bookkeeping here is host-side, so
the policy is unit testable with plain numpy payloads
(tests/test_prefix_cache.py).

**Paged layout (ISSUE 12).**  With ``kv_layout="paged"`` the slot
table's KV lives in one shared physical block pool per layer and this
module becomes the pool's ALLOCATOR: :class:`PagePool` hands out
refcounted page indices, the radix tree's payloads are those indices
(``release_fn``/``on_insert`` keep the pool's refcounts in lockstep
with node lifetime), and eviction frees physical pages instead of
dropping device-array views — no lease-copy dance, and one physical
page serves every slot whose table references it.
"""

import itertools
import logging
import time

import numpy as np

logger = logging.getLogger(__name__)


class PoolExhausted(RuntimeError):
    """The page pool has no free pages left (and the caller's radix
    eviction loop could not free any — everything still referenced)."""


class PagePool(object):
    """Host-side refcounted allocator over a fixed set of physical KV
    pages (the device pools are preallocated ``[num_pages, page_tokens,
    heads, dim]`` arrays; this class only tracks INDICES into them).

    - :meth:`alloc` hands out ``n`` free pages at refcount 1 (the
      allocating slot's reference).
    - :meth:`retain` adds a reference (a second slot installing the
      same page into its block table, or the radix cache committing
      it) — this is exactly the "one physical block serves many slots"
      sharing the contiguous layout had to COPY for.
    - :meth:`release` drops a reference; a page returns to the free
      list only at refcount 0.

    Page 0 (more generally ``reserved`` leading pages) is never handed
    out: idle slots' block tables point at it, so their dead-lane
    decode writes land in a trash page instead of a live one.
    """

    def __init__(self, num_pages, reserved=1, clock=None):
        if int(num_pages) <= int(reserved):
            raise ValueError(
                "num_pages ({0}) must exceed the {1} reserved "
                "page(s)".format(num_pages, reserved)
            )
        self.num_pages = int(num_pages)
        self.reserved = int(reserved)
        self._clock = clock if clock is not None else time.monotonic
        self._refs = np.zeros((self.num_pages,), np.int64)
        # LIFO free list: recently-freed pages are re-handed first
        # (their device lines are the warmest)
        self._free = list(range(self.num_pages - 1, self.reserved - 1, -1))
        # pages mid-flight between the disaggregated prefill and
        # decode programs (serving_disagg): written by prefill, not
        # yet adopted by a slot's table.  Pure accounting — the
        # refcounts above keep the pages alive; this set makes the
        # in-flight population observable (pool_pages_handoff) and
        # lets tests assert every handoff drains.
        self._handoff = set()
        # lease id -> {"owner", "pages" (set), "t0", "deadline_sec"}.
        # A lease names WHO holds a handoff in flight and since when,
        # so an orphaned handoff (its PrefillWorker died/wedged before
        # adopt or abandon) is attributable and reclaimable
        # (:meth:`reap_orphans`) instead of leaking pages forever.
        self._leases = {}
        self._lease_seq = itertools.count(1)

    def available(self):
        return len(self._free)

    def alloc(self, n):
        """``n`` free page indices at refcount 1."""
        n = int(n)
        if n > len(self._free):
            raise PoolExhausted(
                "page pool exhausted: need {0} pages, {1} free of "
                "{2} ({3})".format(
                    n, len(self._free), self.num_pages, self.lease_table()
                )
            )
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def retain(self, pages):
        for p in pages:
            if self._refs[p] <= 0:
                raise ValueError(
                    "retain() on free page {0}".format(int(p))
                )
            self._refs[p] += 1

    def release(self, pages):
        for p in pages:
            if self._refs[p] <= 0:
                raise ValueError(
                    "release() on free page {0}".format(int(p))
                )
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(int(p))

    def refcount(self, page):
        return int(self._refs[page])

    def refcount_census(self):
        """``{page: refcount}`` over every LIVE page — the balance
        probe's view.  A quiesced paged decoder (no in-flight slots,
        no handoffs) must census to exactly its radix cache's
        committed pages at refcount 1 each, with the reserved trash
        page(s) never appearing (tests/test_chaos_serving.py property
        sweep; testing/soak.py probes this continuously)."""
        return {
            int(p): int(self._refs[p])
            for p in np.nonzero(self._refs)[0]
        }

    def begin_handoff(self, pages, owner=None, deadline_sec=None):
        """Tag ``pages`` as mid-flight between the disaggregated
        prefill and decode programs (the PrefillWorker wrote their KV;
        no slot table references them yet).  The pages must be live —
        the worker holds the allocating references.

        Returns a lease id.  ``owner`` names the holder (the request
        id, conventionally) and ``deadline_sec`` bounds how long the
        handoff may stay in flight before :meth:`reap_orphans` treats
        it as orphaned; both optional, so pre-lease callers that
        ignore the return value are unchanged."""
        pages = [int(p) for p in pages]
        for p in pages:
            if self._refs[p] <= 0:
                raise ValueError(
                    "begin_handoff() on free page {0} ({1})".format(
                        p, self.lease_table()
                    )
                )
        for p in pages:
            self._handoff.add(p)
        lease = next(self._lease_seq)
        self._leases[lease] = {
            "owner": owner,
            "pages": set(pages),
            "t0": self._clock(),
            # tfoslint: disable=TFOS004(lease deadline, not request column)
            "deadline_sec": (
                None if deadline_sec is None else float(deadline_sec)
            ),
        }
        return lease

    def end_handoff(self, pages):
        """Clear the in-flight tag — the decode side adopted the pages
        into a slot's block table (or the handoff was abandoned and
        the references released).  Leases drain automatically: a lease
        whose pages all ended is settled and removed."""
        pages = {int(p) for p in pages}
        for p in pages:
            self._handoff.discard(p)
        for lease in [
            k for k, rec in self._leases.items() if rec["pages"] & pages
        ]:
            rec = self._leases[lease]
            rec["pages"] -= pages
            if not rec["pages"]:
                del self._leases[lease]

    def handoff_leases(self, now=None):
        """The live lease table as dicts (owner, age_sec, pages,
        deadline_sec, expired), oldest first — the observable face of
        the handoff protocol, rendered by :meth:`lease_table` and
        swept by :meth:`reap_orphans`."""
        now = self._clock() if now is None else float(now)
        out = []
        for lease, rec in sorted(
            self._leases.items(), key=lambda kv: kv[1]["t0"]
        ):
            age = max(0.0, now - rec["t0"])
            # tfoslint: disable=TFOS004(lease deadline, not request column)
            dl = rec["deadline_sec"]
            out.append({
                "lease": lease,
                "owner": rec["owner"],
                "age_sec": age,
                "pages": len(rec["pages"]),
                # tfoslint: disable=TFOS004(lease deadline, not request column)
                "deadline_sec": dl,
                "expired": dl is not None and age > dl,
            })
        return out

    def lease_table(self, now=None):
        """One-line human summary of live handoff leases, embedded in
        :class:`PoolExhausted` and handoff-path errors so post-mortems
        name the owning request instead of a bare count."""
        rows = self.handoff_leases(now=now)
        if not rows:
            return "no handoff leases"
        return "leases: " + "; ".join(
            "#{0} owner={1} pages={2} age={3:.1f}s{4}".format(
                r["lease"], r["owner"] or "?", r["pages"], r["age_sec"],
                " EXPIRED" if r["expired"] else "",
            )
            for r in rows
        )

    def reap_orphans(self, owner=None, now=None):
        """Reclaim orphaned handoff leases: with ``owner`` given,
        every lease held by that owner; otherwise every lease past its
        deadline.  For each reaped lease the in-flight tag is cleared
        and exactly one reference per page released — the mirror image
        of ``PrefillWorker.abandon`` — so refcounts stay balanced:
        cached-prefix pages were retained once for the handoff and
        private pages were allocated at refcount 1, and a dead worker
        can never have handed either reference to a decode slot.
        Returns the reaped lease summaries (empty when nothing was
        orphaned)."""
        now = self._clock() if now is None else float(now)
        reaped = []
        for lease in list(self._leases):
            rec = self._leases[lease]
            age = max(0.0, now - rec["t0"])
            # tfoslint: disable=TFOS004(lease deadline, not request column)
            dl = rec["deadline_sec"]
            if owner is not None:
                if rec["owner"] != owner:
                    continue
            elif dl is None or age <= dl:
                continue
            pages = sorted(rec["pages"])
            del self._leases[lease]
            for p in pages:
                self._handoff.discard(p)
            self.release(pages)
            reaped.append({
                "lease": lease,
                "owner": rec["owner"],
                "age_sec": age,
                "pages": len(pages),
            })
            logger.warning(
                "page pool reaped orphaned handoff lease #%d "
                "(owner=%s, %d pages, age %.1fs)",
                lease, rec["owner"], len(pages), age,
            )
        return reaped

    def stats(self):
        used = self.num_pages - self.reserved - len(self._free)
        return {
            "pool_pages": self.num_pages,
            "pool_pages_free": len(self._free),
            "pool_pages_used": used,
            # pages referenced by >= 2 holders: the zero-copy sharing
            # the paged layout exists for (refcount-asserted in
            # tests/test_paged_decode.py)
            "pool_pages_shared": int((self._refs >= 2).sum()),
            # pages written by a disaggregated prefill program and not
            # yet adopted by a decode slot (serving_disagg) — drains
            # to 0 when no handoff is in flight
            "pool_pages_handoff": len(self._handoff),
            # live handoff leases (serving_disagg); drains with the
            # handoff set unless a worker orphaned one, in which case
            # reap_orphans() settles it
            "pool_leases": len(self._leases),
        }


class _Node(object):
    """One cached block: ``tokens`` (the edge label), its KV
    ``payload``, and the radix links/bookkeeping."""

    __slots__ = ("key", "parent", "children", "payload", "nbytes",
                 "refs", "last_used")

    def __init__(self, key, parent, payload, nbytes):
        self.key = key
        self.parent = parent
        self.children = {}
        self.payload = payload
        self.nbytes = int(nbytes)
        self.refs = 0
        self.last_used = 0


class Lease(object):
    """A pinned lookup result: the matched path (root-most first) and
    how many tokens it covers.  Hold it across the install dispatches,
    then :meth:`PrefixCache.release` it."""

    __slots__ = ("nodes", "n_tokens")

    def __init__(self, nodes, n_tokens):
        self.nodes = nodes
        self.n_tokens = int(n_tokens)

    @property
    def n_blocks(self):
        return len(self.nodes)

    def payloads(self):
        return [n.payload for n in self.nodes]


def _block_key(tokens):
    """Hashable content key for one block of tokens (dtype-normalized
    so int32/int64 prompts index the same node)."""
    return np.asarray(tokens, np.int32).tobytes()


def pages_for_tokens(n_tokens, page_tokens):
    """Physical KV pages a ``n_tokens``-token context occupies at
    ``page_tokens`` tokens per page (ceiling division; 0 for an empty
    context).  The cost-attribution plane's occupancy unit: the usage
    ledger integrates ``pages_for_tokens(context) × chunk_duration``
    into per-request **page-seconds** (docs/observability.md "Cost
    attribution & usage ledger"), so KV residency is charged in the
    same currency the :class:`PagePool` allocates in."""
    n, p = int(n_tokens), max(1, int(page_tokens))
    return (n + p - 1) // p


#: Canonical affinity-fingerprint width in tokens: the granularity at
#: which the fleet router and the radix cache agree on "same prefix".
#: It matches the default radix ``block_tokens`` (one head block), but
#: is deliberately a module CONSTANT rather than per-cache geometry —
#: two replicas configured with different ``block_tokens`` must still
#: compute the SAME fingerprint for the same prompt, or affinity
#: routing would split a shared prefix across replicas
#: (regression-pinned in tests/test_prefix_cache.py).
FINGERPRINT_TOKENS = 16


def fingerprint(tokens, width=FINGERPRINT_TOKENS):
    """Block-granular prompt fingerprint for prefix-affinity routing
    (docs/serving.md "Fleet routing & rolling deploys").

    Reuses the radix tree's key math (:func:`_block_key` — int32
    content bytes, so int32/int64 prompts agree) over the prompt's
    leading ``width`` tokens, hashed to a stable 64-bit int.  Two
    prompts share a fingerprint iff they share their first ``width``
    tokens — exactly the head block of the default radix geometry, so
    the replica a fingerprint routes to is the replica whose radix
    cache accumulated that prefix family's blocks.  Prompts shorter
    than ``width`` fingerprint their full content (consistent routing
    for short prompts too).  Geometry-independent by construction:
    the width is NOT the cache's ``block_tokens``.
    """
    import hashlib

    tokens = np.asarray(tokens, np.int32).ravel()
    key = _block_key(tokens[:max(1, int(width))])
    return int.from_bytes(
        hashlib.blake2b(key, digest_size=8).digest(), "big"
    )


class PrefixCache(object):
    """Radix/trie index over token prefixes → committed KV blocks.

    Args:
      block_tokens: tokens per cached block (the radix edge width and
        the install/extract granularity on device).
      mem_budget_bytes: HBM budget for cached payloads; inserts evict
        cold leaves to stay under it, and inserts that cannot fit
        (everything pinned) are dropped with a counter bump rather
        than blowing the budget.
      clock: injectable LRU counter (tests); default is a process-wide
        monotonic tick.
      release_fn: optional hook called with a node's payload when the
        node is evicted — the paged layout passes the
        :class:`PagePool`'s release here so an evicted radix block
        frees its physical page (instead of dropping a device-array
        view, the contiguous layout's semantics).
    """

    def __init__(self, block_tokens=16, mem_budget_bytes=256 << 20,
                 clock=None, release_fn=None):
        if int(block_tokens) < 1:
            raise ValueError(
                "block_tokens must be >= 1, got {0}".format(block_tokens)
            )
        self.block_tokens = int(block_tokens)
        self.mem_budget_bytes = int(mem_budget_bytes)
        self._release_fn = release_fn
        self._clock = clock if clock is not None else itertools.count(1).__next__
        self._root = _Node(None, None, None, 0)
        self.bytes_used = 0
        self.n_nodes = 0
        # counters consumed by ServingEngine.stats (deltas per job)
        self.hits = 0          # lookups that matched >= 1 block
        self.misses = 0        # lookups that matched nothing
        self.tokens_saved = 0  # prompt tokens NOT re-prefilled
        self.evictions = 0     # blocks evicted (budget or pressure)
        self.insert_drops = 0  # inserts dropped: budget full of pins
        # fleet telemetry twins (null singletons when disabled): same
        # counts, published into the process registry so the driver's
        # cluster view sees cache behavior (docs/observability.md)
        from tensorflowonspark_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_hits = reg.counter("prefix_cache.hits")
        self._m_misses = reg.counter("prefix_cache.misses")
        self._m_tokens_saved = reg.counter("prefix_cache.tokens_saved")
        self._m_evictions = reg.counter("prefix_cache.evictions")
        self._m_bytes = reg.gauge("prefix_cache.bytes_used")

    # -- lookup / pin ---------------------------------------------------

    def match_blocks(self, tokens, limit_tokens=None):
        """Longest cached path of whole blocks prefixing ``tokens``
        (bounded by ``limit_tokens``), WITHOUT pinning.  Returns the
        node list, root-most first."""
        tokens = np.asarray(tokens, np.int32).ravel()
        n = tokens.shape[0] if limit_tokens is None else min(
            tokens.shape[0], int(limit_tokens)
        )
        b = self.block_tokens
        nodes = []
        cur = self._root
        for i in range(n // b):
            child = cur.children.get(_block_key(tokens[i * b:(i + 1) * b]))
            if child is None:
                break
            nodes.append(child)
            cur = child
        return nodes

    def acquire(self, tokens, limit_tokens=None):
        """Look up the longest cached prefix of ``tokens`` and PIN it
        (refcount along the path).  Returns a :class:`Lease` —
        ``n_tokens == 0`` on a miss.  ``limit_tokens`` caps the match
        (the SlotDecoder passes ``len(prompt) - 1`` so at least one
        real token remains to prefill for the first-token logits)."""
        nodes = self.match_blocks(tokens, limit_tokens)
        now = self._clock()
        for node in nodes:
            node.refs += 1
            node.last_used = now
        matched = len(nodes) * self.block_tokens
        if nodes:
            self.hits += 1
            self.tokens_saved += matched
            self._m_hits.inc()
            self._m_tokens_saved.inc(matched)
        else:
            self.misses += 1
            self._m_misses.inc()
        return Lease(nodes, matched)

    def release(self, lease):
        """Unpin a :class:`Lease` (after the install dispatches are
        enqueued — the device runtime keeps the buffers alive for any
        in-flight computation that read them).  A lease releases
        exactly once."""
        if lease.nodes is None:
            raise ValueError("lease already released")
        for node in lease.nodes:
            if node.refs <= 0:
                raise ValueError("release() without matching acquire()")
            node.refs -= 1
        lease.nodes = None

    # -- insert / evict -------------------------------------------------

    def insert(self, tokens, payloads, first_block, nbytes_per_block,
               on_insert=None):
        """Attach ``payloads`` as blocks ``first_block..`` of the
        ``tokens`` prefix path.  The first ``first_block`` blocks must
        already be cached (they are: ``first_block`` is the lookup's
        match length).  Returns how many blocks were newly inserted —
        existing nodes are left in place (first writer wins; the
        payloads are token-identical by construction).  ``on_insert``
        is called with each payload the tree actually takes ownership
        of (the paged layout retains the page's pool reference there —
        skipped/dropped payloads stay the caller's)."""
        tokens = np.asarray(tokens, np.int32).ravel()
        b = self.block_tokens
        cur = self._root
        for i in range(int(first_block)):
            cur = cur.children[_block_key(tokens[i * b:(i + 1) * b])]
        inserted = 0
        for j, payload in enumerate(payloads):
            i = int(first_block) + j
            key = _block_key(tokens[i * b:(i + 1) * b])
            child = cur.children.get(key)
            if child is None:
                if not self._make_room(int(nbytes_per_block)):
                    self.insert_drops += 1
                    break
                child = _Node(key, cur, payload, nbytes_per_block)
                child.last_used = self._clock()
                cur.children[key] = child
                self.bytes_used += child.nbytes
                self.n_nodes += 1
                inserted += 1
                self._m_bytes.set(self.bytes_used)
                if on_insert is not None:
                    on_insert(payload)
            cur = child
        return inserted

    def _make_room(self, nbytes):
        """Evict cold leaves until ``nbytes`` more fits the budget;
        False when it cannot (budget smaller than the block, or all
        remaining blocks pinned/interior)."""
        if nbytes > self.mem_budget_bytes:
            return False
        while self.bytes_used + nbytes > self.mem_budget_bytes:
            if not self._evict_one():
                return False
        return True

    def _cold_leaves(self):
        out = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not self._root and not node.children \
                    and node.refs == 0:
                out.append(node)
        return out

    def _evict_one(self):
        leaves = self._cold_leaves()
        if not leaves:
            return False
        victim = min(leaves, key=lambda n: n.last_used)
        del victim.parent.children[victim.key]
        victim.parent = None
        if self._release_fn is not None:
            # paged layout: give the physical page back to the pool
            self._release_fn(victim.payload)
        victim.payload = None  # drops the device buffers
        self.bytes_used -= victim.nbytes
        self.n_nodes -= 1
        self.evictions += 1
        self._m_evictions.inc()
        self._m_bytes.set(self.bytes_used)
        return True

    def evict_blocks(self, n=1):
        """Evict up to ``n`` cold leaf blocks (LRU first); returns how
        many were evicted.  The paged layout's allocation path calls
        this under POOL pressure (free pages, not bytes — the
        byte-budget twin is :meth:`evict_cold`)."""
        done = 0
        for _ in range(int(n)):
            if not self._evict_one():
                break
            done += 1
        return done

    def evict_cold(self, target_bytes):
        """Evict cold leaf blocks (LRU first) until ``bytes_used <=
        target_bytes``; the serving engine's degrade policy calls this
        under backlog pressure BEFORE shrinking token budgets.
        Returns the number of blocks evicted."""
        n = 0
        while self.bytes_used > max(0, int(target_bytes)):
            if not self._evict_one():
                break
            n += 1
        return n

    def clear(self):
        """Drop every unpinned block (between jobs / tests)."""
        return self.evict_cold(0)

    # -- introspection --------------------------------------------------

    def fingerprint(self, tokens, width=None):
        """The prompt's affinity fingerprint (see module-level
        :func:`fingerprint`).  The width defaults to the CANONICAL
        :data:`FINGERPRINT_TOKENS`, NOT this cache's ``block_tokens``
        — caches at different block geometries must agree on what
        "same prefix" means, or the router would scatter a shared
        prefix across replicas (regression-pinned in
        tests/test_prefix_cache.py)."""
        return fingerprint(
            tokens, FINGERPRINT_TOKENS if width is None else width
        )

    def page_census(self):
        """Sorted payloads of every committed radix block.  Under the
        paged layout payloads are :class:`PagePool` indices, so this
        is the set of pool pages the radix holds one reference to —
        the soak/property-sweep balance probe compares it against
        :meth:`PagePool.refcount_census` on a quiesced decoder."""
        out, stack = [], [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not self._root and node.payload is not None:
                out.append(node.payload)
        try:
            return sorted(int(p) for p in out)
        except (TypeError, ValueError):
            return out  # contiguous layout: payloads are device arrays

    def stats(self):
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_tokens_saved": self.tokens_saved,
            "evictions": self.evictions,
            "insert_drops": self.insert_drops,
            "bytes_used": self.bytes_used,
            "nodes": self.n_nodes,
        }

    def __len__(self):
        return self.n_nodes
