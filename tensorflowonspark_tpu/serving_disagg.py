"""Disaggregated prefill: the prefill half of a split serving engine.

Prefill and decode have OPPOSITE resource shapes — prefill is one big
compute-bound batched matmul pass over the whole prompt, decode is a
long bandwidth-bound sequence of tiny steps — and interleaving them in
one program makes every decode chunk behind a long admit pay the
prompt's latency (the p99/TTFT tail under mixed prompt lengths).  This
module splits them: :class:`PrefillWorker` runs prefill as ITS OWN
jitted (and, under a mesh, GSPMD-sharded) program, and the chunked
:class:`~tensorflowonspark_tpu.models.transformer.SlotDecoder` stays
the decode scheduler.

The KV handoff between the two programs is a **block-table exchange**
over the shared paged pool (docs/serving.md "Disaggregated
prefill/decode & TP sharding"):

1. the worker allocates a page row from the decoder's
   :class:`~tensorflowonspark_tpu.prefix_cache.PagePool` (cached
   radix prefix pages install as indices, exactly like a unified
   paged admit) and tags it in-flight (``begin_handoff``);
2. its prefill program writes the prompt's KV STRAIGHT INTO the pool
   pages through a 1-row block table and samples the first token;
3. :meth:`SlotDecoder.adopt` installs the page indices into the target
   slot's table row (host bookkeeping) and scatters the slot's state
   vectors — one dispatch that never takes a KV bank operand.

No program on the path copies KV between banks: the pages the prefill
wrote ARE the pages decode reads, which is the "zero-copy ACROSS
programs, not just across slots" property the tests assert via
``last_adopt_dispatches == 1`` + cache-leaf identity across adopt, and
the pool's ``pool_pages_handoff`` stat draining to 0.

The worker deliberately shares the decoder's pool, radix cache, rng
stream and sampling knobs, so a disaggregated engine is token-identical
to the unified one across the whole feature stack (GQA + window +
int8-KV + prefix cache + paged layout) — asserted in
tests/test_serving_disagg.py.
"""

import jax
import jax.numpy as jnp

__all__ = ["Handoff", "PrefillWorker"]


class Handoff(object):
    """One finished prefill, ready for :meth:`SlotDecoder.adopt`.

    ``pages`` is the page-index row holding the prompt's KV (the
    adopting slot's whole table span), ``n_tokens`` the prompt length,
    ``cached_tokens`` the radix-cached prefix depth (telemetry),
    ``first`` the sampled first token — an UNRESOLVED device scalar,
    the same async contract as :meth:`SlotDecoder.admit`'s return.
    """

    __slots__ = ("pages", "n_tokens", "cached_tokens", "first")

    def __init__(self, pages, n_tokens, cached_tokens, first):
        self.pages = list(pages)
        self.n_tokens = int(n_tokens)
        self.cached_tokens = int(cached_tokens)
        self.first = first


class PrefillWorker(object):
    """The prefill-side program of a disaggregated engine.

    Owns ONE jitted program — the canonical-position suffix prefill
    writing through a 1-row block table into the decoder's shared page
    pool (the paged plane's admit program minus the slot-state
    scatter, which moved to the decode side's ``adopt``).  One
    compiled program per suffix bucket, shared by cached hits of every
    depth; the pool cache is donated (linear handle, reassigned on the
    shared decoder every dispatch).

    Under a TP mesh nothing changes here: the decoder's committed
    weight/pool placements make GSPMD shard this program the same way
    it shards decode.
    """

    def __init__(self, decoder):
        if not getattr(decoder, "_paged", False):
            raise ValueError(
                "PrefillWorker needs a paged SlotDecoder "
                "(kv_layout='paged'): the prefill→decode handoff is a "
                "block-table exchange over the shared page pool"
            )
        if getattr(decoder, "_spec", False):
            raise ValueError(
                "disaggregated prefill does not compose with "
                "draft-model speculation (the draft's contiguous banks "
                "live on the decode side only)"
            )
        self.decoder = decoder
        #: program census of the last prefill() — pinned at 1: the
        #: suffix prefill IS the only dispatch (cached pages install
        #: as indices, commits record indices)
        self.last_prefill_dispatches = 0
        self._jit = jax.jit(self._prefill_impl, donate_argnums=(1,))

    def _prefill_impl(self, params, cache, suffix, n, kpref, trow, key):
        """Suffix prefill at canonical positions through a 1-row block
        table: writes the pool pages in place and samples the first
        token from the last real suffix row (``n - kpref - 1``).
        ``n``/``kpref`` are traced — one program per suffix bucket."""
        dec = self.decoder
        logits, mut = dec.model.apply(
            {"params": params, "cache": cache}, suffix, decode=True,
            mutable=["cache"], slot_positions=kpref[None],
            block_tables=trow,
        )
        row = jax.lax.dynamic_slice_in_dim(
            logits, n - kpref - 1, 1, axis=1
        )[:, 0]
        first = dec._sample(row, key)[0]
        return mut["cache"], first

    def prefill(self, prompt):
        """Run one prompt's prefill and return its :class:`Handoff`.

        Mirrors the unified paged admit's pool/radix protocol exactly
        (same leases, same page refcounts, same commit of the prompt's
        new full blocks) — only the slot-state scatter is missing,
        deferred to the adopting decoder.  All dispatches stay async.
        """
        dec = self.decoder
        np = dec._np
        prompt = np.asarray(prompt, np.int32).ravel()
        n = int(prompt.shape[0])
        if n == 0:
            raise ValueError("cannot prefill an empty prompt")
        if n + dec.max_new_tokens > dec.cache_len:
            raise ValueError(
                "prompt ({0}) + max_new_tokens ({1}) exceeds the "
                "engine cache_len={2}".format(
                    n, dec.max_new_tokens, dec.cache_len
                )
            )
        pc, pool = dec.prefix_cache, dec.page_pool
        blk = dec._page_tokens
        if pc is not None:
            # at least one real token must prefill (first-token logits)
            lease = pc.acquire(prompt, limit_tokens=n - 1)
            kpref = lease.n_tokens
            cached_pages = [int(p) for p in lease.payloads()]
        else:
            lease, kpref, cached_pages = None, 0, []
        self.last_prefill_dispatches = 1
        # the handoff holds its own reference to every shared page
        # (the radix may evict the block before the decode side
        # adopts — the refcount keeps the physical page alive)
        pool.retain(cached_pages)
        if lease is not None:
            pc.release(lease)
        private = dec._alloc_pages(
            dec._blocks_per_slot - len(cached_pages)
        )
        row = cached_pages + private
        pool.begin_handoff(row)
        sb = dec._suffix_bucket(n - kpref, kpref)
        suffix = np.zeros((1, sb), np.int32)
        suffix[0, :n - kpref] = prompt[kpref:]
        trow = np.asarray([row], np.int32)
        dec.cache, first = self._jit(
            dec._params, dec.cache, jnp.asarray(suffix), jnp.int32(n),
            jnp.int32(kpref), jnp.asarray(trow), dec._next_key(),
        )
        # commit the prompt's NEW full blocks: their pages already
        # hold the KV (the prefill wrote through the table) —
        # recording the indices in the radix IS the commit, zero
        # copies, zero dispatches (the unified paged admit's rule)
        if pc is not None:
            total_blocks = n // blk
            first_new = len(cached_pages)
            if total_blocks > first_new:
                committed = []
                pc.insert(
                    prompt, row[first_new:total_blocks], first_new,
                    dec._page_nbytes, on_insert=committed.append,
                )
                pool.retain(committed)
        return Handoff(row, n, kpref, first)

    def abandon(self, handoff):
        """Release an un-adopted handoff's pages (admit failed or the
        request expired between prefill and adopt) — the abandon path
        of the handoff protocol, so a crashed adopt can never leak
        pool pages."""
        pool = self.decoder.page_pool
        pool.end_handoff(handoff.pages)
        pool.release(handoff.pages)
        handoff.pages = []
