"""Disaggregated prefill: the prefill half of a split serving engine.

Prefill and decode have OPPOSITE resource shapes — prefill is one big
compute-bound batched matmul pass over the whole prompt, decode is a
long bandwidth-bound sequence of tiny steps — and interleaving them in
one program makes every decode chunk behind a long admit pay the
prompt's latency (the p99/TTFT tail under mixed prompt lengths).  This
module splits them: :class:`PrefillWorker` runs prefill as ITS OWN
jitted (and, under a mesh, GSPMD-sharded) program, and the chunked
:class:`~tensorflowonspark_tpu.models.transformer.SlotDecoder` stays
the decode scheduler.

The KV handoff between the two programs is a **block-table exchange**
over the shared paged pool (docs/serving.md "Disaggregated
prefill/decode & TP sharding"):

1. the worker allocates a page row from the decoder's
   :class:`~tensorflowonspark_tpu.prefix_cache.PagePool` (cached
   radix prefix pages install as indices, exactly like a unified
   paged admit) and tags it in-flight (``begin_handoff``);
2. its prefill program writes the prompt's KV STRAIGHT INTO the pool
   pages through a 1-row block table and samples the first token;
3. :meth:`SlotDecoder.adopt` installs the page indices into the target
   slot's table row (host bookkeeping) and scatters the slot's state
   vectors — one dispatch that never takes a KV bank operand.

No program on the path copies KV between banks: the pages the prefill
wrote ARE the pages decode reads, which is the "zero-copy ACROSS
programs, not just across slots" property the tests assert via
``last_adopt_dispatches == 1`` + cache-leaf identity across adopt, and
the pool's ``pool_pages_handoff`` stat draining to 0.

The worker deliberately shares the decoder's pool, radix cache, rng
stream and sampling knobs, so a disaggregated engine is token-identical
to the unified one across the whole feature stack (GQA + window +
int8-KV + prefix cache + paged layout) — asserted in
tests/test_serving_disagg.py.
"""

import jax
import jax.numpy as jnp

__all__ = [
    "Handoff", "PrefillAbandoned", "PrefillWorker", "PrefillWorkerDead",
]


class PrefillWorkerDead(RuntimeError):
    """The PrefillWorker's program is gone (its device errored or a
    chaos ``kill_prefill`` fault fired).  The engine contains it:
    orphaned handoff leases are reaped, the stranded request re-prefills
    through the unified path, and the worker is rebuilt
    (``ServingEngine.restart_prefill_worker``)."""


class PrefillAbandoned(RuntimeError):
    """Raised INSIDE a wedged prefill dispatch when it finally wakes
    and finds its watchdog already abandoned it — the dispatch must not
    touch the donated cache or its (already reaped) lease pages, so it
    aborts before the program call instead of racing the recovery."""


class Handoff(object):
    """One finished prefill, ready for :meth:`SlotDecoder.adopt`.

    ``pages`` is the page-index row holding the prompt's KV (the
    adopting slot's whole table span), ``n_tokens`` the prompt length,
    ``cached_tokens`` the radix-cached prefix depth (telemetry),
    ``first`` the sampled first token — an UNRESOLVED device scalar,
    the same async contract as :meth:`SlotDecoder.admit`'s return.
    ``owner``/``lease`` name the pool handoff lease holding the pages
    in flight, so handoff-path errors are attributable.
    """

    __slots__ = (
        "pages", "n_tokens", "cached_tokens", "first", "owner", "lease",
    )

    def __init__(self, pages, n_tokens, cached_tokens, first,
                 owner=None, lease=None):
        self.pages = list(pages)
        self.n_tokens = int(n_tokens)
        self.cached_tokens = int(cached_tokens)
        self.first = first
        self.owner = owner
        self.lease = lease


class PrefillWorker(object):
    """The prefill-side program of a disaggregated engine.

    Owns ONE jitted program — the canonical-position suffix prefill
    writing through a 1-row block table into the decoder's shared page
    pool (the paged plane's admit program minus the slot-state
    scatter, which moved to the decode side's ``adopt``).  One
    compiled program per suffix bucket, shared by cached hits of every
    depth; the pool cache is donated (linear handle, reassigned on the
    shared decoder every dispatch).

    Under a TP mesh nothing changes here: the decoder's committed
    weight/pool placements make GSPMD shard this program the same way
    it shards decode.
    """

    def __init__(self, decoder, fault_fn=None, lease_deadline_sec=None):
        if not getattr(decoder, "_paged", False):
            raise ValueError(
                "PrefillWorker needs a paged SlotDecoder "
                "(kv_layout='paged'): the prefill→decode handoff is a "
                "block-table exchange over the shared page pool"
            )
        if getattr(decoder, "_spec", False):
            raise ValueError(
                "disaggregated prefill does not compose with "
                "draft-model speculation (the draft's contiguous banks "
                "live on the decode side only)"
            )
        self.decoder = decoder
        #: program census of the last prefill() — pinned at 1: the
        #: suffix prefill IS the only dispatch (cached pages install
        #: as indices, commits record indices)
        self.last_prefill_dispatches = 0
        #: set by a ``kill_prefill`` chaos fault (or a supervisor that
        #: observed the worker's device die): every subsequent
        #: prefill() refuses with :class:`PrefillWorkerDead` until the
        #: engine rebuilds the worker
        self.dead = False
        #: deadline stamped on this worker's handoff leases (the
        #: engine derives it from its watchdog timeout); None = leases
        #: only reaped by owner, never by age
        self.lease_deadline_sec = lease_deadline_sec
        #: count of prefill() entries — the chaos fault index (same
        #: role as the engine's chunk index for wedge_dispatch)
        self._prefills = 0
        if fault_fn is None:
            from tensorflowonspark_tpu.testing import chaos

            fault_fn = chaos.prefill_fault_fn()
        self._fault = fault_fn
        self._jit = jax.jit(self._prefill_impl, donate_argnums=(1,))

    def _prefill_impl(self, params, cache, suffix, n, kpref, trow, key):
        """Suffix prefill at canonical positions through a 1-row block
        table: writes the pool pages in place and samples the first
        token from the last real suffix row (``n - kpref - 1``).
        ``n``/``kpref`` are traced — one program per suffix bucket."""
        dec = self.decoder
        logits, mut = dec.model.apply(
            {"params": params, "cache": cache}, suffix, decode=True,
            mutable=["cache"], slot_positions=kpref[None],
            block_tables=trow,
        )
        row = jax.lax.dynamic_slice_in_dim(
            logits, n - kpref - 1, 1, axis=1
        )[:, 0]
        first = dec._sample(row, key)[0]
        return mut["cache"], first

    def prefill(self, prompt, owner=None, abandoned_fn=None):
        """Run one prompt's prefill and return its :class:`Handoff`.

        Mirrors the unified paged admit's pool/radix protocol exactly
        (same leases, same page refcounts, same commit of the prompt's
        new full blocks) — only the slot-state scatter is missing,
        deferred to the adopting decoder.  All dispatches stay async.

        ``owner`` (the request id, conventionally) is stamped on the
        pool handoff lease so a fault mid-handoff is attributable and
        reapable by owner.  ``abandoned_fn`` is the supervised-dispatch
        escape hatch: a wedged prefill that wakes after its watchdog
        abandoned it checks the flag and aborts with
        :class:`PrefillAbandoned` BEFORE drawing an rng key or touching
        the donated cache — the recovery path already owns both, and
        the untouched rng stream is what keeps the unified-path
        re-prefill token-identical to a fault-free run.
        """
        if self.dead:
            raise PrefillWorkerDead(
                "prefill worker is dead; the engine must rebuild it "
                "(restart_prefill_worker) before serving prefills"
            )
        dec = self.decoder
        np = dec._np
        prompt = np.asarray(prompt, np.int32).ravel()
        n = int(prompt.shape[0])
        if n == 0:
            raise ValueError("cannot prefill an empty prompt")
        if n + dec.max_new_tokens > dec.cache_len:
            raise ValueError(
                "prompt ({0}) + max_new_tokens ({1}) exceeds the "
                "engine cache_len={2}".format(
                    n, dec.max_new_tokens, dec.cache_len
                )
            )
        pc, pool = dec.prefix_cache, dec.page_pool
        blk = dec._page_tokens
        if pc is not None:
            # at least one real token must prefill (first-token logits)
            lease = pc.acquire(prompt, limit_tokens=n - 1)
            kpref = lease.n_tokens
            cached_pages = [int(p) for p in lease.payloads()]
        else:
            lease, kpref, cached_pages = None, 0, []
        self.last_prefill_dispatches = 1
        # the handoff holds its own reference to every shared page
        # (the radix may evict the block before the decode side
        # adopts — the refcount keeps the physical page alive)
        pool.retain(cached_pages)
        if lease is not None:
            pc.release(lease)
        try:
            private = dec._alloc_pages(
                dec._blocks_per_slot - len(cached_pages)
            )
        except Exception:
            # give back the handoff's cached-prefix references — an
            # exhausted pool must not also leak the shared pages
            pool.release(cached_pages)
            raise
        row = cached_pages + private
        pool_lease = pool.begin_handoff(
            row, owner=owner, deadline_sec=self.lease_deadline_sec
        )
        self._prefills += 1
        if self._fault is not None:
            # chaos gate (kill_prefill / wedge_prefill / leak_lease):
            # fires with the lease already open and the rng stream and
            # donated cache still untouched, so a fault here orphans
            # the lease exactly the way a real mid-handoff death does
            # — and the reaper + unified re-prefill recover
            # token-identically
            self._fault(self._prefills - 1, self)
        if self.dead:
            raise PrefillWorkerDead(
                "prefill worker died mid-handoff (owner={0}, lease "
                "#{1}, {2} pages in flight)".format(
                    owner, pool_lease, len(row)
                )
            )
        if abandoned_fn is not None and abandoned_fn():
            raise PrefillAbandoned(
                "prefill dispatch abandoned by its watchdog "
                "(owner={0}, lease #{1})".format(owner, pool_lease)
            )
        sb = dec._suffix_bucket(n - kpref, kpref)
        suffix = np.zeros((1, sb), np.int32)
        suffix[0, :n - kpref] = prompt[kpref:]
        trow = np.asarray([row], np.int32)
        new_cache, first = self._jit(
            dec._params, dec.cache, jnp.asarray(suffix), jnp.int32(n),
            jnp.int32(kpref), jnp.asarray(trow), dec._next_key(),
        )
        if abandoned_fn is not None and abandoned_fn():
            # abandoned DURING the program call (a genuinely slow
            # dispatch, not a pre-jit wedge): the reaper already owns
            # this lease's pages — never publish the stale cache handle
            # or commit freed pages into the radix from this thread
            raise PrefillAbandoned(
                "prefill dispatch abandoned mid-program "
                "(owner={0}, lease #{1})".format(owner, pool_lease)
            )
        dec.cache = new_cache
        # commit the prompt's NEW full blocks: their pages already
        # hold the KV (the prefill wrote through the table) —
        # recording the indices in the radix IS the commit, zero
        # copies, zero dispatches (the unified paged admit's rule)
        if pc is not None:
            total_blocks = n // blk
            first_new = len(cached_pages)
            if total_blocks > first_new:
                committed = []
                pc.insert(
                    prompt, row[first_new:total_blocks], first_new,
                    dec._page_nbytes, on_insert=committed.append,
                )
                pool.retain(committed)
        return Handoff(row, n, kpref, first, owner=owner,
                       lease=pool_lease)

    def abandon(self, handoff):
        """Release an un-adopted handoff's pages (admit failed or the
        request expired between prefill and adopt) — the abandon path
        of the handoff protocol, so a crashed adopt can never leak
        pool pages."""
        pool = self.decoder.page_pool
        pool.end_handoff(handoff.pages)
        pool.release(handoff.pages)
        handoff.pages = []
