"""Isolated gmm kernel profile on the chip: fwd, dxt (stored-layout dx),
old transposed-copy dx, tgmm (dw) — useful TFLOP/s each, at the moe
bench's real shapes."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np, jax, jax.numpy as jnp
from tensorflowonspark_tpu.ops import gmm

E, D, M = 8, 1024, 4096
N = 4 * 2048 * 2  # tokens*topk at the moe bench shape
bm = 256
T = N // bm
rng = np.random.RandomState(0)
key = jax.random.PRNGKey(0)
x = jax.random.normal(key, (N, D), jnp.bfloat16)
w = jax.random.normal(key, (E, D, M), jnp.bfloat16) * 0.02
dy = jax.random.normal(key, (N, M), jnp.bfloat16)
te = jnp.asarray(np.sort(rng.randint(0, E, T)).astype(np.int32))

flops_fwd = 2 * N * D * M  # useful
# slope method: time a 250-iteration and a 50-iteration chained-scan
# program and divide the DIFFERENCE by 200 — the forcing scalar pull's
# tunnel RTT (~100ms, same order as 100 kernel iterations!) and every
# other constant overhead cancel exactly.  RTT-subtraction variants
# read 205-327 TFLOP/s (over the 197 peak) because the RTT's run-to-run
# variance exceeded the kernel time.
N_LO, N_HI = 50, 250


def timeit_scan(call, arg0):
    def prog_of(n):
        def body(s, _):
            y = call(arg0 + s.astype(arg0.dtype))
            return jnp.ravel(y)[0].astype(jnp.float32) * 0.0, None

        return jax.jit(
            lambda a0: jax.lax.scan(
                body, jnp.float32(0), None, length=n
            )[0]
        )

    p_lo, p_hi = prog_of(N_LO), prog_of(N_HI)
    float(p_lo(arg0))  # compile + settle
    float(p_hi(arg0))

    def once(p):
        t0 = time.perf_counter()
        float(p(arg0))
        return time.perf_counter() - t0

    t_lo = min(once(p_lo) for _ in range(3))
    t_hi = min(once(p_hi) for _ in range(3))
    return max(1e-9, t_hi - t_lo) / (N_HI - N_LO)


out = {}
dt = timeit_scan(lambda a: gmm.gmm_call(a, w, te, bm=bm), x)
out["fwd_tflops"] = round(flops_fwd / dt / 1e12, 1)

dt = timeit_scan(lambda a: gmm.gmm_dxt_call(a, w, te, bm=bm), dy)
out["dx_stored_layout_tflops"] = round(flops_fwd / dt / 1e12, 1)

def _dx_transposed(a):
    # tie the transpose to the chained operand (a tiny non-foldable
    # perturbation): a loop-invariant swapaxes(w,1,2) would be hoisted
    # out of the scan and the row would time the kernel WITHOUT the
    # HBM copy the real backward pays each step
    wt = jnp.swapaxes(
        w + (jnp.ravel(a)[0] * 1e-30).astype(w.dtype), 1, 2
    )
    return gmm.gmm_call(a, wt, te, bm=bm)


dt = timeit_scan(_dx_transposed, dy)
out["dx_transposed_copy_tflops"] = round(flops_fwd / dt / 1e12, 1)

dt = timeit_scan(lambda a: gmm.tgmm_call(a, dy, te, E, bm=bm), x)
out["dw_tgmm_tflops"] = round(flops_fwd / dt / 1e12, 1)

# whole registered backward (dx + dw) with the COTANGENT chained —
# chaining x instead would leave dx loop-invariant and XLA hoists it
# out of the scan (measured "600 TFLOP/s")
_, vjp_fn = jax.vjp(
    lambda xx, ww: gmm.grouped_matmul(xx, ww, te, bm), x, w
)


def _bwd_pair(a):
    # the chained scalar must depend on BOTH cotangents — returning
    # only dx lets XLA dead-code-eliminate the dw tgmm kernel and the
    # row over-reports ~2x
    dxv, dwv = vjp_fn(a)
    return jnp.ravel(dxv)[:1] + jnp.ravel(dwv)[:1]


dt = timeit_scan(_bwd_pair, dy)
out["bwd_dx_plus_dw_tflops"] = round(2 * flops_fwd / dt / 1e12, 1)
out["shapes"] = "E%d D%d M%d N%d bm%d" % (E, D, M, N, bm)
print(json.dumps(out))
