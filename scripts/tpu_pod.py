#!/usr/bin/env python
"""TPU pod bring-up — the deployment-tooling role of the reference's
``scripts/spark_ec2.py`` (launch a cluster, wire the nodes together,
run a workload), re-targeted at Cloud TPU pod slices.

The reference script provisioned EC2 instances and started a Spark
master + workers on them (reference: scripts/spark_ec2.py — cluster
launch, security groups, master/worker bootstrap).  The TPU analogue is
smaller because the substrate does more: a TPU pod slice is already a
named group of hosts with ICI between chips, and ``jax.distributed``
handles rendezvous from one coordinator address, so "bring-up" is:

1. ``create``  — provision the slice (one ``gcloud compute tpus tpu-vm
   create``);
2. ``bootstrap`` — install this framework on every host (``gcloud ...
   ssh --worker=all``);
3. ``run``     — execute a script on every host with the rendezvous
   environment exported (coordinator = worker 0, process id = worker
   index); the in-framework ``parallel.mesh.distributed_init_from_env``
   (called by every ``build_mesh``) reads exactly these variables;
4. ``delete``  — tear the slice down.

Every subcommand supports ``--dry-run``: print the fully rendered
commands without executing anything (also what the unit tests assert
on — this repo's CI has no GCP credentials, the same posture as the
reference which never ran spark_ec2 in CI).

Example:

    python scripts/tpu_pod.py create  --name tfos-pod --zone us-east5-a \\
        --accelerator v5litepod-16 --version v2-alpha-tpuv5-lite
    python scripts/tpu_pod.py bootstrap --name tfos-pod --zone us-east5-a \\
        --repo https://github.com/you/tensorflowonspark-tpu
    python scripts/tpu_pod.py run     --name tfos-pod --zone us-east5-a \\
        -- python examples/mnist/mnist_spark.py --cluster_size 4
    python scripts/tpu_pod.py delete  --name tfos-pod --zone us-east5-a
"""

import argparse
import dataclasses
import shlex
import subprocess
import sys
from typing import Optional

#: coordinator port for jax.distributed rendezvous (any free port; one
#: constant so `run` and the in-framework bootstrap agree)
COORDINATOR_PORT = 8476


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """One pod slice (the spark_ec2 'cluster name + instance type' pair)."""

    name: str
    zone: str
    accelerator: str = "v5litepod-16"
    version: str = "v2-alpha-tpuv5-lite"
    project: Optional[str] = None  # gcloud default when None


def _gcloud_base(cfg):
    del cfg  # project/zone ride in _common_flags
    return ["gcloud", "compute", "tpus", "tpu-vm"]


def _common_flags(cfg):
    flags = ["--zone", cfg.zone]
    if cfg.project:
        flags += ["--project", cfg.project]
    return flags


def render_create(cfg):
    """The `launch_cluster` role (reference: spark_ec2.py launch path)."""
    return [
        _gcloud_base(cfg)
        + ["create", cfg.name]
        + _common_flags(cfg)
        + [
            "--accelerator-type", cfg.accelerator,
            "--version", cfg.version,
        ]
    ]


def render_delete(cfg):
    return [
        _gcloud_base(cfg)
        + ["delete", cfg.name]
        + _common_flags(cfg)
        + ["--quiet"]
    ]


def render_ssh_all(cfg, remote_command):
    """One command fanned out to every host of the slice
    (``--worker=all`` is gcloud's per-host fan-out; the reference
    looped ssh over instances, spark_ec2.py deploy path)."""
    return [
        _gcloud_base(cfg)
        + ["ssh", cfg.name]
        + _common_flags(cfg)
        + ["--worker=all", "--command", remote_command]
    ]


def render_bootstrap(cfg, repo, ref="main"):
    """Install the framework on every host (the setup-and-deploy role
    of the reference's deploy.generic templates)."""
    script = " && ".join(
        [
            "sudo apt-get -y install git || true",
            "rm -rf ~/tfos-tpu",
            "git clone --depth 1 -b {0} {1} ~/tfos-tpu".format(
                shlex.quote(ref), shlex.quote(repo)
            ),
            "pip install -e ~/tfos-tpu",
            "make -C ~/tfos-tpu/native",
        ]
    )
    return render_ssh_all(cfg, script)


def render_run(cfg, argv, workdir="~/tfos-tpu"):
    """Run ``argv`` on every host with the rendezvous env exported.

    TPU VMs expose the slice topology through instance metadata; worker
    0's address is the coordinator.  The exported variables are exactly
    what ``jax.distributed.initialize`` (and this framework's
    ``parallel/mesh.py`` bootstrap) consume: coordinator address plus
    num_processes/process_id, which JAX's TPU backend can also infer
    from the metadata server — they are exported explicitly so the same
    command works on CPU hosts in tests.
    """
    inner = " ".join(shlex.quote(a) for a in argv)
    script = " && ".join(
        [
            # worker 0's internal IP + host count from the slice
            # metadata (endpoints are comma-separated, one per host)
            'EPTS=$(curl -s -H "Metadata-Flavor: Google" '
            '"http://metadata.google.internal/computeMetadata/v1/instance/'
            'attributes/worker-network-endpoints")',
            "COORD=$(echo $EPTS | cut -d, -f1 | cut -d: -f3)",
            "NPROC=$(echo $EPTS | tr , \\\\n | wc -l)",
            'WID=$(curl -s -H "Metadata-Flavor: Google" '
            '"http://metadata.google.internal/computeMetadata/v1/instance/'
            'attributes/agent-worker-number")',
            "cd {0}".format(workdir),
            # all three rendezvous variables: num_processes must be
            # explicit — on hosts where JAX's cluster auto-detect finds
            # nothing, initialize() with only process_id set raises
            "TFOS_COORDINATOR=$COORD:{0} TFOS_PROCESS_ID=$WID "
            "TFOS_NUM_PROCESSES=$NPROC {1}".format(
                COORDINATOR_PORT, inner
            ),
        ]
    )
    return render_ssh_all(cfg, script)


def _execute(commands, dry_run):
    rendered = [" ".join(shlex.quote(c) for c in cmd) for cmd in commands]
    for line in rendered:
        print(line)
    if dry_run:
        return 0
    rc = 0
    for cmd in commands:
        rc = subprocess.call(cmd)
        if rc != 0:
            break
    return rc


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "action", choices=["create", "bootstrap", "run", "delete"]
    )
    parser.add_argument("--name", required=True)
    parser.add_argument("--zone", required=True)
    parser.add_argument("--accelerator", default="v5litepod-16")
    parser.add_argument("--version", default="v2-alpha-tpuv5-lite")
    parser.add_argument("--project", default=None)
    parser.add_argument("--repo", help="git URL for bootstrap")
    parser.add_argument("--ref", default="main", help="git ref for bootstrap")
    parser.add_argument(
        "--dry-run", action="store_true",
        help="print the rendered gcloud commands without executing",
    )
    # `run` takes the per-host command after `--`; argparse.REMAINDER
    # would swallow the option flags too, so collect leftovers instead
    args, extra = parser.parse_known_args(argv)
    args.command = extra

    cfg = PodConfig(
        name=args.name, zone=args.zone, accelerator=args.accelerator,
        version=args.version, project=args.project,
    )
    if args.action == "create":
        cmds = render_create(cfg)
    elif args.action == "delete":
        cmds = render_delete(cfg)
    elif args.action == "bootstrap":
        if not args.repo:
            parser.error("bootstrap requires --repo")
        cmds = render_bootstrap(cfg, args.repo, args.ref)
    else:  # run
        argv_rest = args.command
        if argv_rest and argv_rest[0] == "--":
            argv_rest = argv_rest[1:]
        if not argv_rest:
            parser.error("run requires a command after `--`")
        cmds = render_run(cfg, argv_rest)
    return _execute(cmds, args.dry_run)


if __name__ == "__main__":
    sys.exit(main())
