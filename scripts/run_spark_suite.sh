#!/usr/bin/env bash
# Reproduce the CI `spark` job locally and keep the log as evidence.
#
# The development image for this repo cannot host pyspark (no package
# installs), so the real-Spark suite (tests/test_spark_real.py — a
# local-cluster[2,1,1024] run mirroring the reference's 2-worker
# Standalone posture, /root/reference/test/run_tests.sh:16-27) only
# executes where pyspark + a JDK are present: CI, or any dev machine
# via this script.  The produced ci_logs/spark_*.log is the artifact
# STATUS.md points to; CI uploads the same log as `spark-e2e-log`.
#
# Usage: scripts/run_spark_suite.sh   (from the repo root)
set -euo pipefail

python -c "import pyspark" 2>/dev/null || {
  echo "pyspark is not installed; run where the CI spark job's deps" \
       "are available (pip install pyspark + JDK 17)" >&2
  exit 2
}

mkdir -p ci_logs
log="ci_logs/spark_$(date +%Y%m%d_%H%M%S).log"
set -o pipefail
python -m pytest tests/test_spark_real.py -m spark -x -q -rs | tee "$log"
python - "$log" <<'EOF'
import re
import sys

txt = open(sys.argv[1]).read()
m = re.search(r"(\d+) passed", txt)
assert m and int(m.group(1)) >= 5, (
    "spark e2e suite passed %s tests; expected >= 5" % (m and m.group(1))
)
print("spark suite green; evidence at", sys.argv[1])
EOF
