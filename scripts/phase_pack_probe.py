"""Probe: phase-packed (space-to-depth) equivalent of a 3x3/s1 conv.

Exactness: y[2i+a, 2j+b] = conv3x3(x)[...] must equal the packed conv's
output phase (a,b).  Packed kernel Wp[di',dj', (a'b')C+c, (ab)F+f] =
w[di,dj,c,f] with di = 2*di' + a' - a + 1 (zero where di outside [0,3)).
Then time baseline vs packed per CIFAR stage shape on the TPU.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def s2d(x):
    b, h, w, c = x.shape
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)


def d2s(y):
    b, h, w, c4 = y.shape
    c = c4 // 4
    y = y.reshape(b, h, w, 2, 2, c)
    return y.transpose(0, 1, 3, 2, 4, 5).reshape(b, 2 * h, 2 * w, c)


def pack_kernel(w):
    kh, kw, cin, cout = w.shape
    assert kh == 3 and kw == 3
    wp = np.zeros((3, 3, 4 * cin, 4 * cout), w.dtype)
    for a in range(2):
        for b in range(2):
            for di in range(3):
                for dj in range(3):
                    # absolute offset rel. packed grid
                    ia, ja = a + di - 1, b + dj - 1
                    dip, ap = divmod(ia, 2)
                    djp, bp = divmod(ja, 2)
                    if not (-1 <= dip <= 1 and -1 <= djp <= 1):
                        continue
                    wp[
                        dip + 1, djp + 1,
                        (ap * 2 + bp) * cin:(ap * 2 + bp + 1) * cin,
                        (a * 2 + b) * cout:(a * 2 + b + 1) * cout,
                    ] = w[di, dj]
    return wp


def conv(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )


def phase_to_channel_output(yp, cout):
    # packed conv output [B,H/2,W/2,4F] -> unpacked [B,H,W,F]
    return d2s(
        yp.reshape(yp.shape[:3] + (4, cout)).reshape(
            yp.shape[:3] + (4 * cout,)
        )
    )


if __name__ == "__main__":
    rng = np.random.RandomState(0)
    # exactness check (f32, CPU-precision tolerances on TPU)
    x = rng.randn(2, 32, 32, 16).astype(np.float32)
    w = (rng.randn(3, 3, 16, 16) * 0.1).astype(np.float32)
    y = np.asarray(conv(jnp.asarray(x), jnp.asarray(w)))
    xp = np.asarray(s2d(jnp.asarray(x)))
    wp = pack_kernel(w)
    yp = np.asarray(conv(jnp.asarray(xp), jnp.asarray(wp)))
    y2 = np.asarray(phase_to_channel_output(jnp.asarray(yp), 16))
    print("exact:", np.allclose(y, y2, atol=1e-3, rtol=1e-3),
          float(np.max(np.abs(y - y2))))

    # timing per stage shape, bench batch
    B = 128
    for (hw, c) in ((32, 16), (16, 32), (8, 64)):
        xb = jnp.asarray(
            rng.randn(B, hw, hw, c).astype(np.float32), jnp.bfloat16
        )
        wb = jnp.asarray(
            (rng.randn(3, 3, c, c) * 0.1).astype(np.float32), jnp.bfloat16
        )
        xpb = s2d(xb)
        wpb = jnp.asarray(pack_kernel(np.asarray(wb, np.float32)),
                          jnp.bfloat16)

        def many(f, x_, w_, n=20):
            def body(carry, _):
                return f(carry, w_).astype(x_.dtype), None
            return lax.scan(body, x_, None, length=n)[0]

        for name, xx, ww in (("base", xb, wb), ("packed", xpb, wpb)):
            g = jax.jit(lambda x_, w_, f=conv: many(f, x_, w_))
            r = g(xx, ww); float(jnp.sum(r.astype(jnp.float32)))
            t0 = time.perf_counter()
            for _ in range(5):
                r = g(xx, ww)
            float(jnp.sum(r.astype(jnp.float32)))
            dt = (time.perf_counter() - t0) / 5 / 20
            print("HW%d C%d %s: %.3f ms/conv" % (hw, c, name, dt * 1e3))
