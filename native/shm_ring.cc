// Shared-memory ring buffer: the zero-copy feed staging path.
//
// SURVEY.md §7 'Hard parts: feed-path throughput' calls for "a C++
// ring buffer + async device_put, not JoinableQueues".  This is that
// ring: a single-producer/single-consumer byte ring living in a
// multiprocessing.SharedMemory segment shared by the feeder task
// process and the compute process on one host.  Records are
// length-framed; head/tail are C++11 atomics (lock-free, cross-process
// over shm), so a push and a pop never contend on a lock and data
// crosses processes with exactly two memcpys (in, out) — no manager
// RPC, no pickle round trip through a third process.
//
// Layout (64-byte-aligned header):
//   uint64 magic; uint64 capacity;        // data region size in bytes
//   atomic<uint64> head;                  // next write offset (mod cap)
//   atomic<uint64> tail;                  // next read offset (mod cap)
//   uint64 producer_pid;                  // liveness slot (python layer)
//   uint32 format_tag;                    // record wire-format tag
//   uint8 data[capacity];
//
// format_tag names the RECORD encoding the producer writes (0 = legacy
// pickled blocks only, 1 = dtype-tagged columnar wire records — the
// narrow-dtype plane's self-describing [magic|json header|raw column
// buffers] format).  Consumers read it once at attach and refuse rings
// whose tag they don't understand instead of mis-decoding frames.
//
// Framing: [uint32 len][len bytes], wrapping byte-wise at the region
// end.  A record longer than capacity-8 is rejected (-2).
//
// All functions take the base pointer of the shm segment.

#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t kMagic = 0x54464f5352494e47ull;  // "TFOSRING"

struct Header {
  uint64_t magic;
  uint64_t capacity;
  std::atomic<uint64_t> head;
  std::atomic<uint64_t> tail;
  uint64_t producer_pid;  // written by the python liveness layer
  uint32_t format_tag;    // record wire-format tag (see file comment)
  uint8_t pad[64 - 3 * sizeof(uint64_t) - 2 * sizeof(std::atomic<uint64_t>) -
              sizeof(uint32_t)];
};

static_assert(sizeof(Header) == 64, "header must be one cache line");

inline Header* H(uint8_t* base) { return reinterpret_cast<Header*>(base); }
inline uint8_t* Data(uint8_t* base) { return base + sizeof(Header); }

// copy `n` bytes into the ring at logical offset `pos` (wraps)
inline void RingWrite(uint8_t* data, uint64_t cap, uint64_t pos,
                      const uint8_t* src, uint64_t n) {
  uint64_t off = pos % cap;
  uint64_t first = (off + n <= cap) ? n : cap - off;
  memcpy(data + off, src, first);
  if (n > first) memcpy(data, src + first, n - first);
}

inline void RingRead(const uint8_t* data, uint64_t cap, uint64_t pos,
                     uint8_t* dst, uint64_t n) {
  uint64_t off = pos % cap;
  uint64_t first = (off + n <= cap) ? n : cap - off;
  memcpy(dst, data + off, first);
  if (n > first) memcpy(dst + first, data, n - first);
}

}  // namespace

extern "C" {

// initialize a fresh segment of `total_bytes`; returns usable capacity
// or -1 if the segment is too small.
int64_t shmring_init(uint8_t* base, uint64_t total_bytes) {
  if (total_bytes < sizeof(Header) + 64) return -1;
  Header* h = H(base);
  h->magic = kMagic;
  h->capacity = total_bytes - sizeof(Header);
  h->producer_pid = 0;
  h->format_tag = 0;
  h->head.store(0, std::memory_order_relaxed);
  h->tail.store(0, std::memory_order_release);
  return static_cast<int64_t>(h->capacity);
}

// record wire-format negotiation: the creating/producing side tags the
// segment, consumers verify before decoding.  -3 = bad segment.
int shmring_set_format(uint8_t* base, uint32_t tag) {
  Header* h = H(base);
  if (h->magic != kMagic) return -3;
  h->format_tag = tag;
  return 0;
}

int64_t shmring_format(uint8_t* base) {
  Header* h = H(base);
  if (h->magic != kMagic) return -3;
  return static_cast<int64_t>(h->format_tag);
}

// 0 = ok, -1 = full (retry later), -2 = record too large, -3 = bad segment
int shmring_push(uint8_t* base, const uint8_t* rec, uint64_t len) {
  Header* h = H(base);
  if (h->magic != kMagic) return -3;
  uint64_t cap = h->capacity;
  if (len > UINT32_MAX - 4 || len + 4 > cap) return -2;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  uint64_t tail = h->tail.load(std::memory_order_acquire);
  if (head - tail + len + 4 > cap) return -1;  // not enough free space
  uint32_t len32 = static_cast<uint32_t>(len);
  RingWrite(Data(base), cap, head,
            reinterpret_cast<const uint8_t*>(&len32), 4);
  RingWrite(Data(base), cap, head + 4, rec, len);
  h->head.store(head + 4 + len, std::memory_order_release);
  return 0;
}

// Scatter-gather push: one record assembled from `nparts` segments
// (header + raw column buffers) with a single head advance — the
// zero-pickle columnar path writes numpy buffers straight into the
// ring instead of concatenating them into an intermediate bytes.
// Same return codes as shmring_push.
int shmring_pushv(uint8_t* base, const uint8_t** parts,
                  const uint64_t* lens, uint64_t nparts) {
  Header* h = H(base);
  if (h->magic != kMagic) return -3;
  uint64_t cap = h->capacity;
  uint64_t len = 0;
  for (uint64_t i = 0; i < nparts; ++i) len += lens[i];
  // the frame length field is u32: a >4GiB record would silently wrap
  // and corrupt the ring framing on multi-GiB rings
  if (len > UINT32_MAX - 4 || len + 4 > cap) return -2;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  uint64_t tail = h->tail.load(std::memory_order_acquire);
  if (head - tail + len + 4 > cap) return -1;  // not enough free space
  uint32_t len32 = static_cast<uint32_t>(len);
  RingWrite(Data(base), cap, head,
            reinterpret_cast<const uint8_t*>(&len32), 4);
  uint64_t pos = head + 4;
  for (uint64_t i = 0; i < nparts; ++i) {
    RingWrite(Data(base), cap, pos, parts[i], lens[i]);
    pos += lens[i];
  }
  h->head.store(head + 4 + len, std::memory_order_release);
  return 0;
}

// >=0 = record length copied into out, -1 = empty, -2 = out_cap too
// small (record length returned via *need), -3 = bad segment
int64_t shmring_pop(uint8_t* base, uint8_t* out, uint64_t out_cap,
                    uint64_t* need) {
  Header* h = H(base);
  if (h->magic != kMagic) return -3;
  uint64_t cap = h->capacity;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint64_t head = h->head.load(std::memory_order_acquire);
  if (head == tail) return -1;
  uint32_t len32;
  RingRead(Data(base), cap, tail, reinterpret_cast<uint8_t*>(&len32), 4);
  if (len32 > out_cap) {
    if (need) *need = len32;
    return -2;
  }
  RingRead(Data(base), cap, tail + 4, out, len32);
  h->tail.store(tail + 4 + len32, std::memory_order_release);
  return static_cast<int64_t>(len32);
}

// bytes currently buffered (approximate under concurrency)
int64_t shmring_size(uint8_t* base) {
  Header* h = H(base);
  if (h->magic != kMagic) return -3;
  return static_cast<int64_t>(
      h->head.load(std::memory_order_acquire) -
      h->tail.load(std::memory_order_acquire));
}

}  // extern "C"
