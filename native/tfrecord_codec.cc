// TFRecord framing codec — the native storage layer.
//
// Role parity: the reference shipped a prebuilt Java jar
// (lib/tensorflow-hadoop-1.0-SNAPSHOT.jar) whose
// TFRecordFileInputFormat/OutputFormat implemented this exact framing
// for Spark (used from dfutil.py:39,63 and DFUtil.scala:38,192).  This
// C++ implementation is the TPU build's equivalent, reached from
// Python via ctypes (no pybind11 in the image).
//
// Wire format (TensorFlow's tfrecord):
//   uint64 length           (little-endian)
//   uint32 masked_crc32c(length bytes)
//   byte   data[length]
//   uint32 masked_crc32c(data)
// masked = ((crc >> 15) | (crc << 17)) + 0xa282ead8
//
// CRC32C (Castagnoli) in software, slice-by-8: ~1-2 GB/s/core, enough
// to saturate typical storage; the framing layer is never the
// bottleneck against HBM-bound training.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32c

uint32_t kCrcTable[8][256];

void InitTablesImpl() {
  const uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kCrcTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      crc = kCrcTable[0][crc & 0xff] ^ (crc >> 8);
      kCrcTable[t][i] = crc;
    }
  }
}

// thread-safe one-time init: ctypes releases the GIL during calls, so
// two threads' first CRC computations may race here
void InitTables() {
  static std::once_flag once;
  std::call_once(once, InitTablesImpl);
}

uint32_t Crc32c(const uint8_t* data, size_t n, uint32_t crc = 0) {
  InitTables();
  crc = ~crc;
  // slice-by-8 main loop
  while (n >= 8) {
    uint64_t chunk;
    memcpy(&chunk, data, 8);
    chunk ^= crc;  // fold current crc into the low 4 bytes
    crc = kCrcTable[7][chunk & 0xff] ^
          kCrcTable[6][(chunk >> 8) & 0xff] ^
          kCrcTable[5][(chunk >> 16) & 0xff] ^
          kCrcTable[4][(chunk >> 24) & 0xff] ^
          kCrcTable[3][(chunk >> 32) & 0xff] ^
          kCrcTable[2][(chunk >> 40) & 0xff] ^
          kCrcTable[1][(chunk >> 48) & 0xff] ^
          kCrcTable[0][(chunk >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

inline uint32_t Mask(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

inline uint32_t Unmask(uint32_t masked) {
  uint32_t rot = masked - 0xa282ead8u;
  return (rot >> 17) | (rot << 15);
}

// ---------------------------------------------------------------- writer

struct Writer {
  FILE* f;
};

struct Reader {
  FILE* f;
  std::vector<uint8_t> buf;
  std::string error;
};

}  // namespace

extern "C" {

// crc utilities exposed for tests / python fallback validation
uint32_t tfr_crc32c(const uint8_t* data, uint64_t len) {
  return Crc32c(data, len);
}
uint32_t tfr_masked_crc(const uint8_t* data, uint64_t len) {
  return Mask(Crc32c(data, len));
}

void* tfr_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  return new Writer{f};
}

// append one record; returns 0 on success
int tfr_writer_write(void* handle, const uint8_t* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint64_t len_le = len;  // assume little-endian host (x86/arm TPU VMs)
  uint32_t len_crc = Mask(Crc32c(reinterpret_cast<uint8_t*>(&len_le), 8));
  uint32_t data_crc = Mask(Crc32c(data, len));
  if (fwrite(&len_le, 8, 1, w->f) != 1) return -1;
  if (fwrite(&len_crc, 4, 1, w->f) != 1) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  if (fwrite(&data_crc, 4, 1, w->f) != 1) return -1;
  return 0;
}

int tfr_writer_flush(void* handle) {
  return fflush(static_cast<Writer*>(handle)->f);
}

void tfr_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  fclose(w->f);
  delete w;
}

void* tfr_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  return new Reader{f, {}, {}};
}

// read next record into the reader's buffer.
// returns length >= 0 on success, -1 on EOF, -2 on corruption.
// data pointer is returned via *out (valid until the next call).
int64_t tfr_reader_next(void* handle, const uint8_t** out) {
  Reader* r = static_cast<Reader*>(handle);
  uint64_t len;
  size_t got = fread(&len, 1, 8, r->f);
  if (got == 0) return -1;  // clean EOF
  if (got != 8) { r->error = "truncated length"; return -2; }
  uint32_t len_crc;
  if (fread(&len_crc, 4, 1, r->f) != 1) { r->error = "truncated length crc"; return -2; }
  if (Unmask(len_crc) != Crc32c(reinterpret_cast<uint8_t*>(&len), 8)) {
    r->error = "length crc mismatch";
    return -2;
  }
  if (len > (1ull << 40)) { r->error = "absurd record length"; return -2; }
  r->buf.resize(len);
  if (len && fread(r->buf.data(), 1, len, r->f) != len) {
    r->error = "truncated data";
    return -2;
  }
  uint32_t data_crc;
  if (fread(&data_crc, 4, 1, r->f) != 1) { r->error = "truncated data crc"; return -2; }
  if (Unmask(data_crc) != Crc32c(r->buf.data(), len)) {
    r->error = "data crc mismatch";
    return -2;
  }
  *out = r->buf.data();
  return static_cast<int64_t>(len);
}

const char* tfr_reader_error(void* handle) {
  return static_cast<Reader*>(handle)->error.c_str();
}

void tfr_reader_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  fclose(r->f);
  delete r;
}

}  // extern "C"
