// Batch tf.train.Example -> columnar buffers: the native data-plane
// fast path.
//
// Role parity: the reference's JVM layer converted record batches to
// tensors for feeding/serving (TFModel.scala:51-114 batch2tensors; the
// tensorflow-hadoop jar handled record decode for Spark).  Here a batch
// of serialized Example protos is parsed straight into contiguous
// columnar arrays (one pass, no per-value Python objects), ready for
// np.frombuffer + jax.device_put.
//
// Wire facts used (proto3):
//   Example      { Features features = 1; }
//   Features     { map<string, Feature> feature = 1; }    // entries: k=1,v=2
//   Feature      { oneof { BytesList=1, FloatList=2, Int64List=3 } }
//   FloatList    { repeated float value = 1 [packed] }    // or wire-5 unpacked
//   Int64List    { repeated int64 value = 1 [packed] }    // or wire-0 unpacked
//
// Exposed (extern "C", ctypes):
//   ex_extract_float / ex_extract_int64: fixed-width column over n records
// Return 0 ok; -1 feature missing; -2 wrong kind; -3 width mismatch;
// -4 malformed proto.  Missing policy: a record lacking the feature
// fails (-1) — silent zero-fill would corrupt training data.

#include <cstdint>
#include <cstring>

namespace {

struct Slice {
  const uint8_t* p;
  const uint8_t* end;
};

bool ReadVarint(Slice* s, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (s->p < s->end && shift < 64) {
    uint8_t b = *s->p++;
    v |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

bool Skip(Slice* s, uint32_t wire) {
  uint64_t n;
  switch (wire) {
    case 0:
      return ReadVarint(s, &n);
    case 1:
      if (s->end - s->p < 8) return false;
      s->p += 8;
      return true;
    case 2:
      if (!ReadVarint(s, &n) || static_cast<uint64_t>(s->end - s->p) < n)
        return false;
      s->p += n;
      return true;
    case 5:
      if (s->end - s->p < 4) return false;
      s->p += 4;
      return true;
    default:
      return false;
  }
}

bool ReadLenDelim(Slice* s, Slice* out) {
  uint64_t n;
  if (!ReadVarint(s, &n) || static_cast<uint64_t>(s->end - s->p) < n)
    return false;
  out->p = s->p;
  out->end = s->p + n;
  s->p += n;
  return true;
}

// find feature `name` inside one Example; returns its Feature slice and
// which list kind (1/2/3) wraps it.  0 = found, -1 = missing, -4 = bad.
int FindFeature(Slice rec, const char* name, uint64_t name_len, Slice* out,
                uint32_t* kind) {
  Slice features{nullptr, nullptr};
  while (rec.p < rec.end) {
    uint64_t tag;
    if (!ReadVarint(&rec, &tag)) return -4;
    if ((tag >> 3) == 1 && (tag & 7) == 2) {
      if (!ReadLenDelim(&rec, &features)) return -4;
      // keep scanning: proto allows repeated occurrences; last wins for
      // scalars but Features is a message — entries from later
      // occurrences would be merged.  Handle the common single case by
      // searching each occurrence as we see it.
      Slice f = features;
      while (f.p < f.end) {
        uint64_t etag;
        if (!ReadVarint(&f, &etag)) return -4;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {
          Slice entry;
          if (!ReadLenDelim(&f, &entry)) return -4;
          Slice key{nullptr, nullptr}, value{nullptr, nullptr};
          while (entry.p < entry.end) {
            uint64_t ktag;
            if (!ReadVarint(&entry, &ktag)) return -4;
            uint32_t fld = ktag >> 3, wire = ktag & 7;
            if (fld == 1 && wire == 2) {
              if (!ReadLenDelim(&entry, &key)) return -4;
            } else if (fld == 2 && wire == 2) {
              if (!ReadLenDelim(&entry, &value)) return -4;
            } else if (!Skip(&entry, wire)) {
              return -4;
            }
          }
          if (key.p && value.p &&
              static_cast<uint64_t>(key.end - key.p) == name_len &&
              memcmp(key.p, name, name_len) == 0) {
            // inside Feature: the oneof list
            while (value.p < value.end) {
              uint64_t ftag;
              if (!ReadVarint(&value, &ftag)) return -4;
              uint32_t fld = ftag >> 3, wire = ftag & 7;
              if ((fld >= 1 && fld <= 3) && wire == 2) {
                if (!ReadLenDelim(&value, out)) return -4;
                *kind = fld;
                return 0;
              }
              if (!Skip(&value, wire)) return -4;
            }
            // present but empty Feature message
            out->p = out->end = value.p;
            *kind = 0;
            return 0;
          }
        } else if (!Skip(&f, etag & 7)) {
          return -4;
        }
      }
    } else if (!Skip(&rec, tag & 7)) {
      return -4;
    }
  }
  // no Features message, or the name wasn't among its entries: either
  // way the feature is missing from this record
  return -1;
}

}  // namespace

extern "C" {

// Extract feature `name` as float32 columns: out must hold n*width.
int ex_extract_float(const uint8_t* const* recs, const uint64_t* lens,
                     int64_t n, const char* name, float* out, int64_t width) {
  uint64_t name_len = strlen(name);
  for (int64_t i = 0; i < n; i++) {
    Slice rec{recs[i], recs[i] + lens[i]};
    Slice list;
    uint32_t kind;
    int rc = FindFeature(rec, name, name_len, &list, &kind);
    if (rc != 0) return rc;
    if (kind != 2 && !(kind == 0 && width == 0)) return -2;
    float* dst = out + i * width;
    int64_t got = 0;
    while (list.p < list.end) {
      uint64_t tag;
      if (!ReadVarint(&list, &tag)) return -4;
      uint32_t fld = tag >> 3, wire = tag & 7;
      if (fld == 1 && wire == 2) {  // packed
        Slice packed;
        if (!ReadLenDelim(&list, &packed)) return -4;
        if ((packed.end - packed.p) % 4 != 0) return -4;
        int64_t cnt = (packed.end - packed.p) / 4;
        if (got + cnt > width) return -3;
        memcpy(dst + got, packed.p, cnt * 4);
        got += cnt;
      } else if (fld == 1 && wire == 5) {  // unpacked
        if (list.end - list.p < 4) return -4;
        if (got + 1 > width) return -3;
        memcpy(dst + got, list.p, 4);
        list.p += 4;
        got += 1;
      } else if (!Skip(&list, wire)) {
        return -4;
      }
    }
    if (got != width) return -3;
  }
  return 0;
}

// Extract feature `name` as int64 columns: out must hold n*width.
int ex_extract_int64(const uint8_t* const* recs, const uint64_t* lens,
                     int64_t n, const char* name, int64_t* out,
                     int64_t width) {
  uint64_t name_len = strlen(name);
  for (int64_t i = 0; i < n; i++) {
    Slice rec{recs[i], recs[i] + lens[i]};
    Slice list;
    uint32_t kind;
    int rc = FindFeature(rec, name, name_len, &list, &kind);
    if (rc != 0) return rc;
    if (kind != 3 && !(kind == 0 && width == 0)) return -2;
    int64_t* dst = out + i * width;
    int64_t got = 0;
    while (list.p < list.end) {
      uint64_t tag;
      if (!ReadVarint(&list, &tag)) return -4;
      uint32_t fld = tag >> 3, wire = tag & 7;
      if (fld == 1 && wire == 2) {  // packed varints
        Slice packed;
        if (!ReadLenDelim(&list, &packed)) return -4;
        while (packed.p < packed.end) {
          uint64_t v;
          if (!ReadVarint(&packed, &v)) return -4;
          if (got + 1 > width) return -3;
          dst[got++] = static_cast<int64_t>(v);
        }
      } else if (fld == 1 && wire == 0) {  // unpacked varint
        uint64_t v;
        if (!ReadVarint(&list, &v)) return -4;
        if (got + 1 > width) return -3;
        dst[got++] = static_cast<int64_t>(v);
      } else if (!Skip(&list, wire)) {
        return -4;
      }
    }
    if (got != width) return -3;
  }
  return 0;
}

}  // extern "C"
